"""Collective-schedule extraction, fingerprinting, and verification.

An SPMD program hangs when two ranks disagree about the next collective —
different op, different axis set, different replica groups, or simply a
different order (Horovod guards this operationally with a background
coordinator; PAPERS.md: arXiv 1802.05799). The MPMD direction (arXiv
2412.14374) multiplies the number of per-stage programs whose schedules
must agree. This module makes the schedule a first-class, *checkable*
artifact:

- :func:`extract_from_jaxpr` / :func:`extract_from_hlo_text` pull the
  ordered collective-op sequence — kind, axis names / replica groups,
  payload dtype+shape — out of a traced jaxpr or a lowered/compiled HLO
  dump. Both readers are tolerant (flight.py's torn-tail rule): a
  truncated HLO text or an unknown custom-call collective (a Pallas
  kernel from ``csrc``, a fused op) degrades to a reported note on the
  :class:`Schedule`, never an exception.
- :meth:`Schedule.fingerprint` canonicalizes the sequence into a short
  stable hash — the unit of comparison everywhere else.
- :func:`verify_uniform` checks schedule identity across simulated
  ranks/configs (the elastic re-formation / per-stage-program hang
  class) and names the first divergent op when they differ.
- :func:`verify_bucket_schedule` checks the extracted schedule against
  the deterministic plan ``parallel/collectives.plan_buckets`` promises:
  one ``psum`` (or ``reduce_scatter``+``all_gather`` ring pair) per
  fusion bucket, in sorted-path bucket order.
- :func:`verify_pipeline_pairing` (rule ``pipeline-schedule-pairing``)
  checks a pipeline schedule table (``models/pipeline.build_schedule``)
  for the MPMD divergent-schedule deadlock class: every stage's
  occupancy must be fed by a matching collective-permute edge in the
  same tick's shift, source/target pairs must form a partial
  permutation, and the ring wrap must never collide with an injection.
  :func:`permute_schedule` renders the table's per-tick shift pairs as
  a first-class :class:`Schedule` so it fingerprints like any traced
  program.
- :func:`check_aot_pairing` records (config fingerprint -> schedule
  fingerprint) pairs in a sidecar registry and flags any config
  fingerprint that maps to two different schedules — the invariant that
  makes a ``perf/aot.py`` cache hit safe: equal keys must mean equal
  collective schedules.

Pure-stdlib except where a caller hands in jaxprs; importing this module
never imports jax (``tools/doctor.py`` runs the AST passes jax-free).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Any, Optional, Sequence

from distributeddeeplearning_tpu.analysis import finding

# jaxpr primitive name -> canonical kind. psum_scatter traces as
# `reduce_scatter` on current jax; older generations bound psum through
# rewrite variants — map every spelling to one canonical kind so a jax
# upgrade cannot silently change fingerprints.
_PRIM_KINDS = {
    "psum": "psum", "psum2": "psum", "psum_invariant": "psum",
    "pmean": "pmean", "pmax": "pmax", "pmin": "pmin",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
    "all_gather": "all_gather", "all_gather_invariant": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute", "pshuffle": "ppermute",
    "collective_permute": "ppermute",
}

# HLO instruction opcodes that move data across participants. Async pairs
# (-start/-done) count once, at the -start.
_HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast", "custom-call")
_HLO_OP_RE = re.compile(
    r"=\s*(?:\(?\s*)?(?:(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[0-9,]*)\]"
    r"[^\s]*\s+)?(?P<op>" + "|".join(_HLO_COLLECTIVES) +
    r")(?P<async>-start|-done)?\(")
_HLO_TARGET_RE = re.compile(r'custom_call_target="(?P<target>[^"]+)"')


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in program order — the fingerprint's unit."""

    kind: str                               # canonical kind or custom-call
    axes: Optional[tuple[str, ...]] = None  # named axes (jaxpr source)
    groups: Optional[str] = None            # replica_groups (HLO source)
    shape: Optional[tuple[int, ...]] = None
    dtype: Optional[str] = None
    note: Optional[str] = None              # e.g. unknown custom-call target
    pairs: Optional[tuple] = None           # ppermute (src, dst) pairs —
                                            # jaxpr `perm` / HLO
                                            # source_target_pairs

    def describe(self) -> str:
        where = (",".join(self.axes) if self.axes
                 else (self.groups or "?"))
        payload = (f"{self.dtype or '?'}{list(self.shape)}"
                   if self.shape is not None else "?")
        extra = f" [{self.note}]" if self.note else ""
        if self.pairs is not None:
            extra = f" pairs={list(map(list, self.pairs))}" + extra
        return f"{self.kind}({where}, {payload}){extra}"

    def canonical(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


@dataclasses.dataclass(frozen=True)
class Schedule:
    """The ordered collective sequence of ONE program, plus any reader
    notes (``errors`` report, they never raise — a partial schedule from
    torn input is still comparable and still fingerprints)."""

    ops: tuple[CollectiveOp, ...]
    source: str = "?"                    # jaxpr | hlo | label
    errors: tuple[str, ...] = ()

    def fingerprint(self) -> str:
        blob = json.dumps([op.canonical() for op in self.ops],
                          sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def describe(self) -> str:
        lines = [f"{i:3d}. {op.describe()}" for i, op in enumerate(self.ops)]
        lines += [f"  !! {e}" for e in self.errors]
        return "\n".join(lines) or "(no collectives)"


# ---------------------------------------------------------------------------
# jaxpr extraction
# ---------------------------------------------------------------------------

def _normalize_axes(value) -> Optional[tuple[str, ...]]:
    if value is None:
        return None
    if isinstance(value, (list, tuple)):
        return tuple(str(a) for a in value)
    return (str(value),)


def _sub_jaxprs(value):
    """Every (Closed)Jaxpr reachable from one eqn param value — how
    shard_map/pjit/scan/cond/custom_vjp bodies are traversed without
    naming each primitive's param layout (which drifts across jax
    versions)."""
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(v)
        elif hasattr(v, "eqns"):                      # core.Jaxpr
            yield v
        elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            yield v.jaxpr                             # core.ClosedJaxpr


def extract_from_jaxpr(jaxpr_like: Any) -> Schedule:
    """Ordered collective ops of a jaxpr (recursing into shard_map / pjit
    / scan / cond / custom_vjp sub-jaxprs). Accepts a ``ClosedJaxpr``, a
    raw ``Jaxpr``, or anything carrying a ``.jaxpr``. Tolerant of
    jax-version drift: an eqn whose params cannot be read is reported on
    ``errors`` and skipped, never raised."""
    ops: list[CollectiveOp] = []
    errors: list[str] = []
    root = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    if not hasattr(root, "eqns"):
        return Schedule(ops=(), source="jaxpr",
                        errors=(f"not a jaxpr: {type(jaxpr_like).__name__}",))
    seen: set[int] = set()

    def walk(jx) -> None:
        if id(jx) in seen:           # defensive: shared sub-jaxprs once
            return
        seen.add(id(jx))
        for eqn in jx.eqns:
            try:
                name = eqn.primitive.name
                kind = _PRIM_KINDS.get(name)
                if kind is not None:
                    params = eqn.params
                    axes = _normalize_axes(params.get("axes")
                                           if "axes" in params
                                           else params.get("axis_name"))
                    pairs = None
                    if kind == "ppermute" and params.get("perm") is not None:
                        # The (source, target) pairs ARE the schedule for a
                        # permute — two stage programs that disagree here
                        # park forever (pipeline-schedule-pairing class).
                        pairs = tuple((int(a), int(b))
                                      for a, b in params["perm"])
                    aval = getattr(eqn.invars[0], "aval", None) \
                        if eqn.invars else None
                    ops.append(CollectiveOp(
                        kind=kind, axes=axes, pairs=pairs,
                        shape=(tuple(int(d) for d in aval.shape)
                               if aval is not None else None),
                        dtype=(str(aval.dtype) if aval is not None
                               else None)))
                for value in eqn.params.values():
                    for sub in _sub_jaxprs(value):
                        walk(sub)
            except Exception as exc:  # noqa: BLE001 — jax drift tolerated
                errors.append(f"eqn unreadable "
                              f"({type(exc).__name__}: {exc})")
    try:
        walk(root)
    except Exception as exc:  # noqa: BLE001
        errors.append(f"jaxpr walk aborted ({type(exc).__name__}: {exc})")
    return Schedule(ops=tuple(ops), source="jaxpr", errors=tuple(errors))


def schedule_of(fn, *args, **kwargs) -> Schedule:
    """Trace ``fn`` at ``args`` and extract its schedule. The one place
    this module touches jax — import deferred so the AST-only callers
    (doctor) stay jax-free."""
    import jax
    try:
        jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    except Exception as exc:  # noqa: BLE001 — report, never crash the lint
        return Schedule(ops=(), source="jaxpr",
                        errors=(f"trace failed "
                                f"({type(exc).__name__}: {exc})",))
    return extract_from_jaxpr(jaxpr)


# ---------------------------------------------------------------------------
# HLO-text extraction (tolerant reader)
# ---------------------------------------------------------------------------

def _balanced_braces(text: str, start: int) -> Optional[str]:
    """The ``{...}`` group starting at ``start`` (nested braces counted);
    None when the text ends before it closes — a torn dump."""
    depth = 0
    for i in range(start, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return text[start:i + 1]
        elif c == "\n" and depth == 0:
            return None
    return None

# Custom-call targets known to be collectives-in-disguise; anything else
# is recorded as an opaque custom-call with a note (a Pallas kernel from
# csrc/, a fused op) — part of the schedule, tolerated, never fatal.
_KNOWN_CUSTOM_COLLECTIVES = ("allreduce", "all_reduce", "allgather",
                             "all_gather", "reducescatter",
                             "reduce_scatter", "alltoall", "all_to_all",
                             "permute")


def extract_from_hlo_text(text: str) -> Schedule:
    """Ordered collective ops of a lowered/compiled HLO dump.

    Mirrors flight.py's torn-tail rule: a truncated dump (a crashed
    compile, a cut ``as_text()`` pipe) parses up to the tear and reports
    it; a custom-call with an unrecognized target is recorded with a note
    rather than rejected — the analyzer must degrade gracefully on
    kernels it has never heard of."""
    ops: list[CollectiveOp] = []
    errors: list[str] = []
    if not isinstance(text, str):
        return Schedule(ops=(), source="hlo",
                        errors=(f"not text: {type(text).__name__}",))
    lines = text.splitlines()
    if text and not text.endswith("\n") and lines:
        errors.append(f"possibly truncated dump: last line "
                      f"({lines[-1].strip()[:40]!r}...) has no newline; "
                      f"parsed through it best-effort")
    for n, line in enumerate(lines, 1):
        try:
            m = _HLO_OP_RE.search(line)
            if not m:
                continue
            if m.group("async") == "-done":
                continue  # counted at -start
            op = m.group("op")
            groups = None
            gi = line.find("replica_groups=")
            if gi >= 0:
                groups = _balanced_braces(line, line.find("{", gi))
                if groups is None:
                    errors.append(f"line {n}: replica_groups torn "
                                  f"mid-brace; op kept without groups")
            pairs = None
            pi = line.find("source_target_pairs=")
            if pi >= 0:
                blob = _balanced_braces(line, line.find("{", pi))
                if blob is None:
                    errors.append(f"line {n}: source_target_pairs torn "
                                  f"mid-brace; op kept without pairs")
                else:
                    pairs = tuple(
                        (int(a), int(b))
                        for a, b in re.findall(r"\{(\d+),(\d+)\}", blob))
            shape = None
            if m.group("dims") is not None:
                dims = m.group("dims")
                shape = tuple(int(d) for d in dims.split(",")) if dims \
                    else ()
            if op == "custom-call":
                tm = _HLO_TARGET_RE.search(line)
                target = tm.group("target") if tm else "?"
                if not any(k in target.lower()
                           for k in _KNOWN_CUSTOM_COLLECTIVES):
                    # Opaque kernel: schedule-relevant only if it hides a
                    # collective we cannot see — record, note, move on.
                    ops.append(CollectiveOp(
                        kind="custom-call", groups=groups, shape=shape,
                        dtype=m.group("dtype"), pairs=pairs,
                        note=f"unknown target {target!r} (tolerated)"))
                    continue
                ops.append(CollectiveOp(kind=f"custom-call:{target}",
                                        groups=groups, shape=shape,
                                        dtype=m.group("dtype"), pairs=pairs))
                continue
            ops.append(CollectiveOp(kind=op, groups=groups, shape=shape,
                                    dtype=m.group("dtype"), pairs=pairs))
        except Exception as exc:  # noqa: BLE001 — torn lines are expected
            errors.append(f"line {n} unreadable "
                          f"({type(exc).__name__}: {exc})")
    return Schedule(ops=tuple(ops), source="hlo", errors=tuple(errors))


# ---------------------------------------------------------------------------
# Verification passes
# ---------------------------------------------------------------------------

def verify_uniform(schedules: dict[str, Schedule]) -> list[dict]:
    """Schedule identity across ranks/configs: every label must carry the
    same fingerprint. On divergence the finding names the first op index
    where two labels disagree — the op a hang would park on."""
    findings: list[dict] = []
    if len(schedules) < 2:
        return findings
    items = sorted(schedules.items())
    ref_label, ref = items[0]
    for label, sched in items[1:]:
        if sched.fingerprint() == ref.fingerprint():
            continue
        idx = next((i for i, (a, b)
                    in enumerate(zip(ref.ops, sched.ops)) if a != b),
                   min(len(ref.ops), len(sched.ops)))
        a = ref.ops[idx].describe() if idx < len(ref.ops) else "(end)"
        b = sched.ops[idx].describe() if idx < len(sched.ops) else "(end)"
        findings.append(finding(
            "collectives", "schedule-divergence",
            f"collective schedules diverge between {ref_label!r} and "
            f"{label!r} at op {idx}: {a} vs {b} — an SPMD dispatch of "
            f"these programs deadlocks at that op"))
    return findings


def verify_bucket_schedule(schedule: Schedule, plan, algorithm: str,
                           axis_size: int) -> list[dict]:
    """The extracted schedule of an ``all_reduce`` over ``plan`` must be
    exactly the planner's promise: buckets in sorted-path order, one
    ``psum`` each (or a ``reduce_scatter``+``all_gather`` pair for the
    ring form). Anything else means the planner and the traced program
    have drifted apart — the determinism the AOT cache leans on."""
    per_bucket = (("psum",) if algorithm == "psum" or axis_size <= 1
                  else ("reduce_scatter", "all_gather"))
    expected = list(per_bucket) * len(plan.buckets)
    got = [op.kind for op in schedule.ops]
    if got == expected:
        return []
    return [finding(
        "collectives", "bucket-order",
        f"bucket schedule mismatch vs parallel/collectives planner: "
        f"expected {len(plan.buckets)} bucket(s) x {per_bucket} = "
        f"{expected}, traced program issues {got}")]


def permute_schedule(pipeline_schedule) -> Schedule:
    """The activation-shift collective-permute sequence of one pipeline
    schedule table (``models/pipeline.build_schedule``) as a first-class
    :class:`Schedule`: one ``ppermute`` over the ``pipeline`` axis per
    tick, carrying that tick's (source, target) pairs. This is the
    schedule a per-stage MPMD program would have to issue verbatim — it
    fingerprints like a traced program, so ddl-lint can record it and
    bench records can name the shift pattern they measured under."""
    ops = tuple(
        CollectiveOp(kind="ppermute", axes=("pipeline",),
                     pairs=pipeline_schedule.shift_pairs(t.index))
        for t in pipeline_schedule.ticks)
    return Schedule(ops=ops, source=f"pipeline:{pipeline_schedule.name}")


def verify_pipeline_pairing(label: str, sched) -> list[dict]:
    """Rule ``pipeline-schedule-pairing``: the MPMD divergent-schedule
    deadlock class, checked on the host-side tick table before any trace.

    ``sched`` is a ``models/pipeline.PipelineSchedule`` (duck-typed:
    ``num_stages``/``num_microbatches``/``virtual_stages``, ``ticks``
    with ``occupancy``/``inject_mb``/``emit_mb``, ``shift_pairs``).
    Each stage's program is generated from this one table; the checks
    below are exactly the ways independently-generated per-stage views
    can disagree and park a rank on a permute forever:

    - pairs must form a partial permutation (no stage sends or receives
      twice in one shift) over real stage ids;
    - the ring wrap (P-1, 0) must be absent on inject ticks — stage 0
      cannot take the wrap and a fresh microbatch in the same shift;
    - dataflow continuity: work at stage k tick t must have sat at the
      predecessor stage at tick t-1 (or been injected), and the shift
      entering tick t must carry the matching (src, k) edge — a missing
      edge is a receive with no matching send;
    - emission/injection bookkeeping: ``emit_mb`` fires exactly when the
      last stage finishes the last chunk, and every microbatch is
      injected and emitted exactly once.
    """
    findings: list[dict] = []
    p = sched.num_stages
    v = getattr(sched, "virtual_stages", 1)

    def fail(msg: str) -> None:
        findings.append(finding(
            "collectives", "pipeline-schedule-pairing", f"{label}: {msg}"))

    prev_occ = (None,) * p
    for t, tick in enumerate(sched.ticks):
        try:
            pairs = tuple(tuple(e) for e in sched.shift_pairs(tick.index))
        except Exception as exc:  # noqa: BLE001 — report, keep linting
            fail(f"tick {t}: shift_pairs unreadable "
                 f"({type(exc).__name__}: {exc})")
            break
        srcs = [e[0] for e in pairs]
        dsts = [e[1] for e in pairs]
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            fail(f"tick {t}: permute pairs {pairs} are not a partial "
                 f"permutation — some stage must send or receive twice "
                 f"in one shift")
        bad = [e for e in pairs
               if not (0 <= e[0] < p and 0 <= e[1] < p)]
        if bad:
            fail(f"tick {t}: permute pairs {bad} name stages outside "
                 f"0..{p - 1}")
        pair_set = set(pairs)
        if tick.inject_mb is not None and (p - 1, 0) in pair_set:
            fail(f"tick {t}: wrap pair ({p - 1}, 0) scheduled on an "
                 f"inject tick — stage 0 would receive the ring wrap and "
                 f"the fresh microbatch in the same shift")
        for k, occ in enumerate(tick.occupancy):
            if occ is None:
                continue
            mb, c = occ
            if k == 0 and c == 0:
                if tick.inject_mb != mb:
                    fail(f"tick {t}: stage 0 works microbatch {mb} chunk "
                         f"0 but inject_mb={tick.inject_mb} — its input "
                         f"was never injected")
                continue
            src = k - 1 if k else p - 1
            want = (mb, c) if k else (mb, c - 1)
            if (src, k) not in pair_set:
                fail(f"tick {t}: stage {k} needs microbatch/chunk {want} "
                     f"from stage {src} but the shift carries no "
                     f"({src}, {k}) pair — stage {k} waits on a send "
                     f"stage {src}'s program never issues")
            if t == 0 or prev_occ[src] != want:
                held = prev_occ[src] if t else None
                fail(f"tick {t}: stage {k} expects {want} from stage "
                     f"{src} but stage {src} held {held} at tick "
                     f"{t - 1} — per-stage schedules disagree")
        tail = tick.occupancy[p - 1]
        want_emit = (tail[0] if tail is not None and tail[1] == v - 1
                     else None)
        if tick.emit_mb != want_emit:
            fail(f"tick {t}: emit_mb={tick.emit_mb} but stage {p - 1} "
                 f"holds {tail} (expected emit {want_emit})")
        prev_occ = tick.occupancy
    m = sched.num_microbatches
    injected = sorted(t.inject_mb for t in sched.ticks
                      if t.inject_mb is not None)
    emitted = sorted(t.emit_mb for t in sched.ticks
                     if t.emit_mb is not None)
    if injected != list(range(m)):
        fail(f"injection covers {injected}, expected each of 0..{m - 1} "
             f"exactly once")
    if emitted != list(range(m)):
        fail(f"emission covers {emitted}, expected each of 0..{m - 1} "
             f"exactly once")
    return findings


def plan_is_deterministic(tree_builder, plan_buckets, *,
                          bucket_bytes: int) -> list[dict]:
    """Insertion-order independence of the bucket planner: ``tree_builder``
    must return the same logical tree under different container insertion
    orders; the plans (and thus schedules) must be identical."""
    import random
    base = plan_buckets(tree_builder(shuffle=None),
                        bucket_bytes=bucket_bytes)
    for seed in (1, 2):
        rng = random.Random(seed)
        other = plan_buckets(tree_builder(shuffle=rng),
                             bucket_bytes=bucket_bytes)
        if (base.paths, base.buckets) != (other.paths, other.buckets):
            return [finding(
                "collectives", "bucket-order",
                f"plan_buckets is insertion-order dependent (seed {seed}): "
                f"{base.buckets} vs {other.buckets} — two hosts building "
                f"the same gradient tree in different dict orders would "
                f"issue different collective schedules")]
    return []


# ---------------------------------------------------------------------------
# AOT pairing registry (config fingerprint <-> schedule fingerprint)
# ---------------------------------------------------------------------------

REGISTRY_SIDECAR = "schedule_fingerprints"


def check_aot_pairing(config_fp: str, program: str, schedule_fp: str,
                      registry_path: Optional[str] = None,
                      record: bool = True) -> list[dict]:
    """Cross-check a (perf/aot.py config fingerprint, program name) pair
    against the recorded schedule fingerprint. A divergence means an AOT
    cache hit keyed by that config could execute a different collective
    schedule than the one on record — exactly the pairing the cache's
    "equal keys => equal programs" contract forbids. First sighting is
    recorded (when ``record``), matches are silent."""
    from distributeddeeplearning_tpu.observability import sidecars
    target = registry_path or REGISTRY_SIDECAR
    side = sidecars.read(target) or {}
    pairs = side.get("pairs") if isinstance(side.get("pairs"), dict) else {}
    key = f"{config_fp}/{program}"
    prior = pairs.get(key)
    if prior is not None and prior != schedule_fp:
        return [finding(
            "collectives", "aot-schedule-pairing",
            f"config fingerprint {config_fp} program {program!r} now "
            f"traces schedule {schedule_fp} but {prior} is on record — "
            f"an AOT cache hit under this key would pair a cached "
            f"executable with a divergent collective schedule "
            f"(delete the registry entry after an intentional change)")]
    if prior is None and record:
        pairs = dict(pairs)
        pairs[key] = schedule_fp
        sidecars.write(target, {"pairs": pairs})
    return []


def simulate_ranks(make_schedule, ranks: Sequence[int]) -> dict[str, Schedule]:
    """Trace one schedule per simulated rank. SPMD programs must not
    branch on the process index; this surfaces the ones that do.
    ``make_schedule(rank)`` is called with ``jax.process_index`` patched
    to return ``rank`` (the env-var route launch.py children use is
    resolved before tracing, so patching the query function is the
    faithful simulation)."""
    import unittest.mock

    import jax
    out: dict[str, Schedule] = {}
    for rank in ranks:
        with unittest.mock.patch.object(jax, "process_index",
                                        return_value=int(rank)):
            out[f"rank{rank}"] = make_schedule(rank)
    return out
