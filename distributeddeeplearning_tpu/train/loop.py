"""The model-agnostic training loop behind ``train.py``.

One loop serves every acceptance config (BASELINE.json:6-12): it selects the
parallel execution style (explicit-collective DP for CNNs, GSPMD for
transformer workloads with tp/sp), builds the data source, and drives the
compiled step with JSONL metrics — the role the reference's per-framework
``src/train-script.py`` files played (SURVEY.md §2 #1-#3), minus the
framework forks.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import (TrainConfig,
                                                resolve_mlm_max_predictions,
                                                resolve_precision)
from distributeddeeplearning_tpu import data as datalib
from distributeddeeplearning_tpu.data import synthetic
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.observability import anomaly as anomalylib
from distributeddeeplearning_tpu.observability import flight as flightlib
from distributeddeeplearning_tpu.observability import health, sidecars, telemetry
from distributeddeeplearning_tpu.observability import metrics as metricslib
from distributeddeeplearning_tpu.observability import straggler as stragglib
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib
from distributeddeeplearning_tpu.parallel import zero as zerolib
from distributeddeeplearning_tpu.perf import aot as aotlib
from distributeddeeplearning_tpu.perf import compile_cache as cachelib
from distributeddeeplearning_tpu.robustness import faults as faultslib
from distributeddeeplearning_tpu.train import checkpoint as ckptlib
from distributeddeeplearning_tpu.train import optim, steps
from distributeddeeplearning_tpu.train import state as statelib
from distributeddeeplearning_tpu.train.state import TrainState
from distributeddeeplearning_tpu.utils.logging import MetricLogger


def _dtype(config: TrainConfig):
    # The model's compute dtype comes from the precision policy; with no
    # explicit policy this resolves to config.dtype (legacy behavior).
    compute = resolve_precision(config).compute_dtype
    return jnp.bfloat16 if compute == "bfloat16" else jnp.float32


def steps_per_epoch(config: TrainConfig) -> Optional[int]:
    """Explicit ``config.steps_per_epoch``, else derived from the dataset's
    train-split size (ImageNet: 1,281,167; an imagefolder ``data_dir``:
    counted once from disk), else None (step-based runs)."""
    if config.steps_per_epoch:
        return config.steps_per_epoch
    if config.data.data_dir:
        # Imagefolder layout (train/<class>/<files>): count the actual
        # corpus — it wins over the canonical ImageNet constant, which is
        # only right for the full dataset (a TFRecord data_dir has train-*
        # shards, no train/ dir, and raises here). folder_index is the
        # loaders' own lru_cached, extension-filtered listing, so the
        # derived epoch length agrees with the batches they yield and the
        # walk is shared, not repeated. Epoch-cadenced eval then works on
        # any on-disk corpus (the graded-corpus convergence leg needs it).
        try:
            from distributeddeeplearning_tpu.data.imagenet import (
                folder_index)
            n = len(folder_index(config.data.data_dir, "train")[0])
            return max(n // config.global_batch_size, 1)
        except FileNotFoundError:
            pass
    if config.data.dataset == "imagenet":
        from distributeddeeplearning_tpu.data.imagenet import TRAIN_SPLIT_SIZE
        return max(TRAIN_SPLIT_SIZE // config.global_batch_size, 1)
    return None


def uses_gspmd(config: TrainConfig, input_kind: str) -> bool:
    """Transformers (or any config with tp/sp axes) take the GSPMD path;
    pure-DP CNNs take the explicit shard_map+psum path. An ``fsdp`` axis
    alone forces GSPMD *unless* ``optimizer_sharding='zero3'`` — zero3 folds
    the GSPMD fsdp parameter-sharding rule into the explicit path's bucket
    planner (parallel/zero.py), chunk-sharding params over BOTH dp axes."""
    p = config.parallel
    if input_kind == "tokens" or p.model > 1 or p.seq > 1:
        return True
    return p.fsdp > 1 and config.optimizer_sharding != "zero3"


def _host_offload_kind(mesh) -> Optional[str]:
    """The host memory kind for --opt-state-offload, or None when the
    runtime can't place arrays there. Requires an addressable pinned_host
    memory on the mesh devices (TPU runtimes expose it; the CPU backend's
    default memory IS host RAM, so offload there is meaningless and reports
    unsupported) plus Sharding.with_memory_kind."""
    try:
        dev = next(iter(mesh.devices.flat))
        kinds = {m.kind for m in dev.addressable_memories()}
        probe = shardlib.replicated(mesh)
        if not hasattr(probe, "with_memory_kind"):
            return None
    except Exception:
        return None
    return "pinned_host" if "pinned_host" in kinds else None


def build(config: TrainConfig, total_steps: int):
    """Construct (mesh, model, batch sharding, state, train_step, sched, rng)
    for a config. The data source is NOT built here — real pipelines must be
    positioned at the post-restore start step, so ``run`` creates it after
    checkpoint restore."""
    spec = model_spec(config.model)
    _ = config.per_device_batch  # early, friendly divisibility error
    if config.optimizer_sharding not in ("none", "zero1", "zero2", "zero3"):
        raise ValueError(
            f"unknown optimizer_sharding {config.optimizer_sharding!r}; "
            f"expected one of 'none', 'zero1', 'zero2', 'zero3'")
    if (config.optimizer_sharding != "none"
            and uses_gspmd(config, spec.input_kind)
            and not (config.optimizer_sharding == "zero2"
                     and config.parallel.pipeline > 1)):
        raise ValueError(
            f"optimizer_sharding={config.optimizer_sharding!r} applies to "
            "the explicit-DP shard_map path only (image model, no tp/sp "
            "axes — and no fsdp axis except under zero3, which absorbs "
            "it); the GSPMD path shards state via NamedSharding rules "
            "instead. Exception: zero2 composes with a pipelined model "
            "(parallel.pipeline > 1), sharding optimizer state over each "
            "stage's DP group (docs/pipeline.md)")
    if config.attention_impl == "flash" and config.parallel.seq > 1:
        raise ValueError(
            "attention_impl='flash' is incompatible with seq-axis "
            "parallelism (it needs the full sequence per device); use "
            "attention_impl='ring' for seq>1")
    mesh = meshlib.make_mesh(config.parallel, backend=config.backend)
    dtype = _dtype(config)
    if spec.input_kind == "tokens":
        kw: dict = dict(vocab_size=config.data.vocab_size, dtype=dtype,
                        seq_len=config.data.seq_len)
    else:
        kw = dict(num_classes=config.data.num_classes, dtype=dtype)
    # Attention/remat knobs apply to any transformer (BERT/GPT/ViT); CNN
    # builders reject them loudly (TypeError names the kwarg) rather than
    # silently ignoring the flag.
    if config.attention_impl:
        kw["attention_impl"] = config.attention_impl
    if config.remat:
        kw["remat"] = True
    if config.fused_bn:
        kw["fused_bn"] = True
    if config.fused_block:
        kw["fused_block"] = True
    if config.fused_conv3:
        kw["fused_conv3"] = True
    if config.sync_bn:
        # Cross-replica BN needs the named mesh axes of the explicit
        # shard_map path; the GSPMD path has no manual axes to pmean over.
        if uses_gspmd(config, spec.input_kind):
            raise ValueError(
                "sync_bn requires the pure-DP shard_map path (image model, "
                "no tp/sp/fsdp axes); this config takes the GSPMD path")
        import inspect
        if "bn_axis_name" not in inspect.signature(spec.build).parameters:
            raise ValueError(
                f"--sync-bn: model {config.model!r} has no BatchNorm to "
                f"synchronize (supported: resnet*/densenet* families)")
        kw["bn_axis_name"] = steps.DATA_AXES
    if config.pipeline_microbatches:
        kw["pipeline_microbatches"] = config.pipeline_microbatches
    if config.pipeline_schedule != "gpipe":
        kw["pipeline_schedule"] = config.pipeline_schedule
    if config.pipeline_virtual_stages != 1:
        kw["pipeline_virtual_stages"] = config.pipeline_virtual_stages
    model = spec.build(**kw)

    # A mesh axis nothing maps onto silently duplicates compute across its
    # groups (devices wasted, no error from XLA) — reject up front, like the
    # flash/seq check above.
    mcfg = getattr(model, "cfg", None)
    stages = getattr(mcfg, "pipeline_stages", 1)
    experts = getattr(mcfg, "num_experts", 0)
    if config.pipeline_microbatches is not None:
        if config.pipeline_microbatches < 1:
            raise ValueError(
                f"pipeline_microbatches={config.pipeline_microbatches} "
                f"must be >= 1")
        if stages <= 1:
            # Same loud-reject rule as the CNN builders for attn/remat:
            # a knob nothing consumes must not silently do nothing.
            raise ValueError(
                f"pipeline_microbatches set but model {config.model!r} is "
                f"not pipelined (pipeline_stages={stages}); use a *_pp "
                f"model")
    if (config.pipeline_schedule != "gpipe"
            or config.pipeline_virtual_stages != 1) and stages <= 1:
        raise ValueError(
            f"pipeline_schedule={config.pipeline_schedule!r} / "
            f"pipeline_virtual_stages={config.pipeline_virtual_stages} set "
            f"but model {config.model!r} is not pipelined "
            f"(pipeline_stages={stages}); use a *_pp model")
    if config.parallel.pipeline > 1 and stages % config.parallel.pipeline:
        raise ValueError(
            f"parallel.pipeline={config.parallel.pipeline} but model "
            f"{config.model!r} has pipeline_stages={stages}; use a pipelined "
            f"model (e.g. bert_base_pp) whose stage count is divisible by "
            f"the mesh axis")
    if config.parallel.expert > 1 and (experts == 0
                                       or experts % config.parallel.expert):
        raise ValueError(
            f"parallel.expert={config.parallel.expert} but model "
            f"{config.model!r} has num_experts={experts}; use an MoE model "
            f"(e.g. bert_base_moe) whose expert count is divisible by the "
            f"mesh axis")

    stage = config.optimizer_sharding
    sharded = stage in ("zero1", "zero2", "zero3")
    # Under any ZeRO stage the optimizer sees 1/N chunks, so its norm-based
    # pieces (global clip, LARS/LAMB trust ratios) must psum over the DP
    # axes — on the explicit shard_map path only. The GSPMD zero2+pipeline
    # composition is one logical program with no manual axes to psum over;
    # XLA inserts any cross-shard reduction the update math needs.
    explicit_sharded = sharded and not uses_gspmd(config, spec.input_kind)
    tx, sched = optim.make_optimizer(
        config.optimizer, config.global_batch_size, total_steps,
        steps_per_epoch(config),
        shard_axes=steps.DATA_AXES if explicit_sharded else None)
    bn_batch = config.per_device_batch // max(config.grad_accum_steps, 1)
    if config.sync_bn:
        # SyncBN pools statistics across the DP shards: the effective
        # statistics batch is the whole (micro)batch, not the shard's.
        bn_batch *= config.parallel.data * config.parallel.fsdp
    if (spec.input_kind == "image" and jax.process_index() == 0
            and (bn_batch == 1
                 or (config.grad_accum_steps > 1 and bn_batch < 32))):
        import warnings

        # warnings.warn (not a raw stderr print): dedupes across repeat
        # builds and lets deliberate small-batch harnesses filter it.
        # bn_batch == 1 is a measured failure mode, not hypothetical:
        # single-sample BN with a 1x1 final feature map normalizes every
        # feature to exactly beta, collapsing logits to uniform (loss pins
        # at ln(num_classes), BN grads go to zero). Per-shard BN is
        # intentional (per-GPU BN under Horovod); the fix is a bigger
        # per-shard batch, not synced statistics.
        detail = ("training can silently stall at uniform logits; increase "
                  "--batch-size, reduce the data-parallel axis, or pool "
                  "statistics across shards with --sync-bn"
                  if bn_batch == 1 else "consider lowering --accum")
        warnings.warn(
            f"BatchNorm statistics will be computed over only {bn_batch} "
            f"example(s) (per_device_batch={config.per_device_batch}, "
            f"grad_accum_steps={config.grad_accum_steps}); {detail}",
            UserWarning, stacklevel=2)
    rng = jax.random.key(config.seed)

    seq_dim = 1 if spec.input_kind == "tokens" else None
    batch_shd = shardlib.batch_sharding(mesh, seq_dim=seq_dim)

    if uses_gspmd(config, spec.input_kind):
        # Shapes-only example for init; synthetic regardless of data mode.
        example = synthetic.make_source(
            config, spec.input_kind, sharding=batch_shd,
            objective=spec.objective).batch(0)
        # Same AOT executable cache as the explicit-DP path below: a warm
        # boot of an identical config (pipelined runs included — the
        # schedule is part of the fingerprint) deserializes the step with
        # zero retraces instead of re-tracing the whole tick loop. Created
        # BEFORE init so the init program rides the same cache — on a
        # re-formed elastic attempt the init compile is pure spawn_s
        # outage (restore overwrites its values), so it loads warm too.
        aot = aotlib.StepExecutableCache.for_config(
            config, total_steps=total_steps)
        state, shardings = steps.init_sharded_state(
            model, tx, mesh, config, example, rng, spec.input_kind,
            aot=aot)
        train_step = steps.make_gspmd_train_step(
            model, tx, mesh, config, shardings, spec.input_kind,
            spec.objective, aot=aot)
        train_step.aot = aot
    else:
        def variables_fn(rng):
            if spec.input_kind == "tokens":
                return model.init(
                    {"params": rng, "dropout": rng},
                    jnp.zeros((1, config.data.seq_len), jnp.int32),
                    train=False)
            size = config.data.image_size
            return model.init(
                {"params": rng}, jnp.zeros((1, size, size, 3), dtype),
                train=False)

        replicated = shardlib.replicated(mesh)
        layout = converter = params_struct = None
        if sharded:
            dp_size = mesh.shape["data"] * mesh.shape["fsdp"]
            params_struct = jax.eval_shape(variables_fn, rng)["params"]
            layout, _ = zerolib.layout_from_options(
                params_struct, dp_size, options=config.allreduce)
            offload_kind = None
            if getattr(config, "opt_state_offload", False):
                offload_kind = _host_offload_kind(mesh)
                if offload_kind is None and jax.process_index() == 0:
                    print("# warning: --opt-state-offload requested but "
                          "this backend exposes no addressable host memory "
                          "kind (pinned_host) — optimizer state stays in "
                          "device memory (docs/zero_sharding.md)",
                          file=sys.stderr, flush=True)
            converter = zerolib.ZeroStateConverter(
                tx, params_struct, layout, mesh, steps.DATA_AXES,
                stage=3 if stage == "zero3" else 1,
                opt_memory_kind=offload_kind)

        def init_fn(rng):
            variables = variables_fn(rng)
            params = variables["params"]
            # ZeRO: optimizer state is born in the chunked global layout
            # (each leaf padded+raveled to chunk*N); out_shardings below
            # then scatter it 1/N per device — it is never materialized
            # replicated. Under zero3 the params (and EMA) themselves are
            # born in that layout too.
            opt_params = (zerolib.to_chunked(params, layout) if sharded
                          else params)
            if stage == "zero3":
                params = opt_params
            return TrainState.create(
                params=params, opt_state=tx.init(opt_params),
                batch_stats=variables.get("batch_stats"),
                ema_params=(params if config.optimizer.ema_decay > 0
                            else None),
                loss_scale=steps.init_loss_scale(config))

        if sharded:
            abstract = jax.eval_shape(init_fn, rng)
            out_shd = jax.tree_util.tree_map(lambda _: replicated, abstract)
            out_shd = out_shd.replace(opt_state=converter.opt_shardings())
            if stage == "zero3":
                out_shd = out_shd.replace(
                    params=converter.param_shardings(abstract.params))
                if abstract.ema_params is not None:
                    out_shd = out_shd.replace(
                        ema_params=converter.param_shardings(
                            abstract.ema_params))
        else:
            out_shd = replicated
        state = jax.jit(init_fn, out_shardings=out_shd)(rng)
        # AOT executable cache (perf/aot.py): keyed by the config
        # fingerprint + total_steps (the LR schedule bakes the horizon into
        # the program), so a restart attempt or re-launch of the same config
        # deserializes the step instead of retracing it.
        aot = aotlib.StepExecutableCache.for_config(
            config, total_steps=total_steps)
        train_step = steps.make_dp_train_step(
            model, tx, mesh, config, spec.input_kind, spec.objective,
            state_like=state, aot=aot, zero_layout=layout,
            params_struct=params_struct)
        train_step.zero_converter = converter
        train_step.aot = aot

    return mesh, model, batch_shd, state, train_step, sched, rng


def _run_ramp(config: TrainConfig, stages, *, total_steps, logger,
              warmup_steps, eval_batches, return_state,
              restore_for_eval) -> dict[str, Any]:
    """Staged global-batch ramp (arXiv 1711.04325 recipe): run each stage
    as its own segment at the stage batch — the per-stage LR follows for
    free from the linear-scaling rule, because ``make_optimizer`` scales
    the base LR by stage_batch / reference_batch when each segment builds.

    Stages chain through the checkpoint dir when one is configured (every
    boundary lands on the checkpoint cadence by construction, so a stage
    transition IS an ordinary resume — elastic re-formation and
    cross-degree resume compose unchanged), or by carrying the final state
    in process when there is none (quick benches). The returned summary is
    the final stage's — steady state at the target batch — plus a
    ``batch_ramp`` block describing the staging."""
    live = [st for st in stages if st.start_step < total_steps]
    if not live:
        live = stages[-1:]
    carried = None
    summary: dict[str, Any] = {}
    stage_meta = []
    for k, st in enumerate(live):
        end = total_steps if st.end_step is None else min(st.end_step,
                                                          total_steps)
        cfg_s = config.replace(global_batch_size=st.batch)
        if k > 0 and config.checkpoint_dir:
            cfg_s = cfg_s.replace(resume=True)
        last = k == len(live) - 1
        want_state = (return_state and last) or (
            not config.checkpoint_dir and not last)
        summary = run(cfg_s, total_steps=end, logger=logger,
                      warmup_steps=warmup_steps, eval_batches=eval_batches,
                      return_state=want_state,
                      restore_for_eval=restore_for_eval,
                      _ramp_stage=True, _carried_state=carried)
        carried = summary.get("state")
        if not (return_state and last):
            summary.pop("state", None)
        stage_meta.append({
            "batch": int(st.batch),
            "start_step": int(st.start_step),
            "end_step": int(end),
            "examples_per_sec": summary.get("examples_per_sec"),
        })
    summary["batch_ramp"] = {"spec": config.batch_ramp,
                             "stages": stage_meta}
    return summary


def run(config: TrainConfig, *, total_steps: int,
        logger: Optional[MetricLogger] = None,
        warmup_steps: int = 0, eval_batches: int = 0,
        return_state: bool = False,
        restore_for_eval: bool = False,
        _ramp_stage: bool = False,
        _carried_state: Optional[TrainState] = None) -> dict[str, Any]:
    """Train for ``total_steps``; returns a summary with throughput.

    ``warmup_steps`` are excluded from timing (compile + first-step cost),
    matching the reference benchmark harness semantics (SURVEY.md §3.4).
    With ``config.checkpoint_dir`` set, saves every
    ``checkpoint_every_steps`` (async) plus a final save, and — when
    ``config.resume`` — restores the newest checkpoint and continues from
    its step, replaying the deterministic data stream from there.
    ``eval_batches > 0`` enables periodic + final held-out eval
    (SURVEY.md §3.5): sharded top-1 for image models, mean per-token loss
    (perplexity) for token models.
    """
    t_origin = time.perf_counter()  # time_to_first_step_s measures from here
    if not _ramp_stage and not restore_for_eval:
        # Stage segments re-enter run() with a per-stage batch size that
        # deliberately differs from the ramp's final batch — only the
        # top-level call parses (and validates) the schedule.
        ramp = optim.parse_batch_ramp(
            getattr(config, "batch_ramp", None),
            final_batch=config.global_batch_size,
            checkpoint_every=(config.checkpoint_every_steps
                              if config.checkpoint_dir else 0))
        if ramp is not None:
            return _run_ramp(config, ramp, total_steps=total_steps,
                             logger=logger, warmup_steps=warmup_steps,
                             eval_batches=eval_batches,
                             return_state=return_state,
                             restore_for_eval=restore_for_eval)
    owns_logger = logger is None
    logger = logger or MetricLogger()
    # A caller-reused logger (in-process restart harnesses) must not turn
    # the wall time spent between runs — teardown, restore, recompile —
    # into this run's first throughput sample.
    logger.reset_throughput()
    # Telemetry is configured BEFORE the first compile so the collective
    # layers' trace-time bucket spans land in the buffer; export runs in the
    # finally below, so a faulting run (crash/SIGTERM/abort) still writes
    # its trace — the runs a post-mortem needs most.
    tele = telemetry.configure(
        trace_dir=config.trace_dir, trace_steps=config.trace_steps,
        max_events=config.trace_max_events,
        process_index=jax.process_index())
    # Flight recorder (observability/flight.py): the crash-surviving half
    # of observability. config.flight_dir overrides the launcher-exported
    # DDL_FLIGHT_DIR; with neither set the disabled singleton makes every
    # record() a no-op. Configured before the first compile so the
    # collective layers' one-shot plan events land in the record.
    flight = flightlib.configure_from_env(
        host=jax.process_index(),
        directory=getattr(config, "flight_dir", None))
    metricslib.configure(run_id=flight.run_id)
    # Persistent compile cache (perf/compile_cache.py): pointed at the
    # shared directory BEFORE any compile, and re-exported through the
    # environment so launcher children and restart attempts inherit it.
    cachelib.activate(getattr(config, "compile_cache_dir", None))
    spec = model_spec(config.model)
    mesh, model, batch_shd, state, train_step, sched, rng = build(
        config, total_steps)
    # Roofline denominators for every log-cadence record and the summary:
    # analytic FLOPs/example x job peak (per-chip spec x device count) —
    # the %-of-peak axis of observability/perf_report.py. Annotation only:
    # unknown model or chip leaves the logger without a roofline.
    try:
        from distributeddeeplearning_tpu.models import flops as flopslib
        mlm_pred = (resolve_mlm_max_predictions(
            config.data.mlm_max_predictions, config.data.seq_len,
            spec.objective) if spec.input_kind == "tokens" else 0)
        _per_ex = flopslib.train_flops_per_example(
            config.model, seq_len=config.data.seq_len,
            mlm_positions=mlm_pred)
        _peak = flopslib.peak_flops(
            jax.devices()[0].device_kind,
            resolve_precision(config).compute_dtype)
        logger.set_roofline(
            _per_ex, _peak * jax.device_count() if _peak else None)
    except Exception:
        pass

    ckpt = ckptlib.Checkpointer.create(
        config, converter=getattr(train_step, "zero_converter", None))
    try:
        return _run_inner(
            config, spec, mesh, model, batch_shd, state, train_step, sched,
            rng, ckpt, logger, total_steps=total_steps,
            warmup_steps=warmup_steps, eval_batches=eval_batches,
            return_state=return_state, restore_for_eval=restore_for_eval,
            t_origin=t_origin, carried_state=_carried_state)
    except BaseException as exc:
        # Fsync'd BEFORE teardown: even if the finally below wedges, the
        # flight record already explains how the run ended (SIGKILL skips
        # this too, of course — but then the last fault/step event stands).
        flight.record("abort", error=type(exc).__name__,
                      detail=str(exc)[:300])
        raise
    finally:
        if ckpt is not None:
            ckpt.close()  # releases the async-checkpointing executor
        if owns_logger:
            logger.close()  # guaranteed JSONL/TB handle release
        trace_file = tele.export()
        if trace_file is not None:
            print(f"# telemetry trace written to {trace_file}",
                  file=sys.stderr, flush=True)
        flight.close()


def _run_inner(config, spec, mesh, model, batch_shd, state, train_step, sched,
               rng, ckpt, logger, *, total_steps, warmup_steps, eval_batches,
               return_state, restore_for_eval=False,
               t_origin=None, carried_state=None) -> dict[str, Any]:
    if t_origin is None:
        t_origin = time.perf_counter()
    # Fault plan (robustness/faults.py): config.fault_plan + the per-child
    # DDL_FAULT_PLAN env + the legacy fail_at_step shim, filtered to this
    # restart attempt. Empty plan (the default) => injector is None and the
    # hot loop runs zero fault-injection code.
    fault_plan = faultslib.resolve(config)
    fault_plan.validate(total_steps, checkpoint_dir=config.checkpoint_dir)
    start_step = 0
    if carried_state is not None:
        # In-process batch-ramp chaining (no checkpoint dir): adopt the
        # previous stage's final state — same mesh, model, and state
        # structure; only the batch shape and LR scale changed — and pick
        # the loop position up from its step counter.
        state = carried_state
        start_step = int(jax.device_get(state.step))
    resolved_loader = datalib.resolve_loader(config, spec.input_kind)
    live_degree = meshlib.data_parallel_degree(config.parallel)
    # The explicit-DP step carries its stage as an attribute; the GSPMD
    # zero2∘pipeline composition shards via NamedSharding rules and has no
    # such attribute, so fall back to the configured stage — the stream
    # metadata (and the cross-axis announcement below) must name the stage
    # that actually ran, whichever path built the step.
    live_stage = (getattr(train_step, "zero_stage", None)
                  or config.optimizer_sharding or "none")
    live_pp = int(config.parallel.pipeline)
    prior_meta: dict = {}
    if ckpt is not None:
        # Pin the environment-dependent loader resolution to the checkpoint:
        # a resume that would silently switch pipelines (different shuffle
        # order) fails loudly instead (ADVICE r1 #1).
        # opt_state_layout documents the on-disk optimizer-state format:
        # ALWAYS canonical (parameter-shaped leaves) — zero1 runs gather on
        # save (parallel/zero.py) — which is what makes checkpoints
        # interchangeable across optimizer-sharding modes and DP degrees. A
        # future layout change would clash here loudly instead of silently
        # mis-restoring.
        # global_batch_size is the fixed point of elastic re-formation: the
        # DEGREE may change between attempts (mesh_degree below is
        # informational, rewritten each run), but the global batch must not
        # — gradients are allreduce-means, so a fixed batch keeps the
        # trajectory bitwise across degrees, while a changed batch silently
        # changes the optimization problem. Eval-only consumers are exempt
        # (they feed no optimizer).
        meta = {"loader": resolved_loader, "opt_state_layout": "canonical"}
        if not restore_for_eval:
            # Under a batch ramp the strict key is the ramp's FINAL batch
            # (constant across every stage segment, and equal to a plain
            # unramped config's global_batch_size): a mid-ramp stage resume
            # and an unramped continuation at the target batch both pass,
            # while resuming at a genuinely different problem still fails
            # loudly. The ramp spec itself rides in the informational set.
            meta["global_batch_size"] = int(optim.ramp_final_batch(config))
        # optimizer_sharding / pipeline_degree join mesh_degree as
        # informational (rewritten each run): the canonical layout makes
        # checkpoints interchangeable across ZeRO stages and pipeline
        # degrees, so a cross-axis re-formation is announced, not refused.
        prior_meta = ckpt.verify_or_record_stream_meta(
            meta, update={"mesh_degree": live_degree,
                          "optimizer_sharding": live_stage,
                          "pipeline_degree": live_pp,
                          "batch_ramp": optim.ramp_describe(config)})
    # The membership event of a re-formed elastic attempt (exported by the
    # launcher as DDL_ELASTIC_EVENT): detect_t is CLOCK_MONOTONIC at fault
    # detection, the same clock telemetry.now_s() reads in this process, so
    # the first post-resume step closes the reconfiguration_time_s span.
    # Read BEFORE restore: a re-formed attempt overlaps its warm compile
    # against the restore below.
    elastic_event = health.read_elastic_event()
    if ckpt is not None and config.resume:
        warm_thread = None
        if (elastic_event is not None and not restore_for_eval
                and getattr(train_step, "warm", None) is not None):
            # Re-formation fast path: kick the train-step compile off on a
            # background thread (abstract avals from the pre-restore state
            # template + one throwaway batch at the latest-step hint) while
            # orbax restores — the detect->first-step outage then pays
            # max(restore, compile), not their sum. Failures silently leave
            # the cold path in place, like the evaluator's warm compile.
            hint = ckpt.latest_step()
            if hint is not None and int(hint) < total_steps:
                try:
                    warm_src = datalib.make_source(
                        config, spec.input_kind, batch_shd,
                        start_step=int(hint), objective=spec.objective)
                    warm_batch = warm_src.batch(int(hint))
                    state_struct = jax.tree_util.tree_map(
                        lambda x: jax.ShapeDtypeStruct(
                            x.shape, x.dtype, sharding=x.sharding), state)
                    warm_thread = threading.Thread(
                        target=train_step.warm,
                        args=(state_struct, warm_batch, rng),
                        daemon=True, name="ddl-reform-warm-compile")
                    warm_thread.start()
                except Exception:  # noqa: BLE001 - warm-up is optional
                    warm_thread = None
        # restore_for_eval: params/BN/step only, fresh optimizer state — an
        # eval-only consumer must not have to repeat the training run's
        # optimizer flags to satisfy the full-state structure match.
        restored = (ckpt.restore_latest_for_eval(state) if restore_for_eval
                    else ckpt.restore_latest(state))
        if restored is not None:
            # Warm-restart aliasing safety, for EVERY restore: on CPU,
            # orbax-restored arrays can ALIAS host memory the restore
            # machinery owns (zero-copy device_put). A step that donates
            # them then produces outputs aliasing memory orbax later frees
            # and reuses — the live state (and every checkpoint saved from
            # it) silently turns to garbage a few steps into the resumed
            # run. Observed through the plain jit path too, not just a
            # directly-called AOT executable (perf/aot.py), so the copy is
            # unconditional: one bitwise-identical device copy breaks the
            # alias and the buffers are XLA-owned, like a fresh init's.
            state = ckptlib.device_copy(restored)
            start_step = int(jax.device_get(state.step))
            prior_degree = prior_meta.get("mesh_degree")
            if (prior_degree is not None
                    and int(prior_degree) != live_degree):
                # Elastic cross-degree resume (launch.py --elastic): the
                # checkpoint was written at another DP degree; the
                # converter's canonical layout already restored it bitwise
                # onto THIS mesh. Loud, because a degree change outside
                # elastic mode is operator error worth noticing.
                if jax.process_index() == 0:
                    print(f"# elastic: resumed a degree-{prior_degree} "
                          f"checkpoint onto a degree-{live_degree} mesh "
                          f"(canonical layout; global batch unchanged)",
                          file=sys.stderr, flush=True)
                telemetry.get().instant(
                    "elastic:cross_degree_resume", step=start_step,
                    degree_before=int(prior_degree),
                    degree_after=live_degree)
            # Cross-AXIS resume: the previous attempt ran a different ZeRO
            # stage and/or pipeline degree. The canonical (parameter-shaped)
            # on-disk layout restored bitwise onto this plan; announce so an
            # operator reading the log sees the axes crossed, not just the
            # degree.
            prior_stage = prior_meta.get("optimizer_sharding")
            prior_pp = prior_meta.get("pipeline_degree")
            axis_changes = []
            if prior_stage is not None and str(prior_stage) != live_stage:
                axis_changes.append(
                    f"optimizer sharding {prior_stage} -> {live_stage}")
            if prior_pp is not None and int(prior_pp) != live_pp:
                axis_changes.append(f"pipeline {int(prior_pp)} -> {live_pp}")
            if axis_changes:
                if jax.process_index() == 0:
                    print("# elastic: cross-axis resume — "
                          + ", ".join(axis_changes)
                          + " (canonical layout; trajectory preserved "
                            "through the converter)",
                          file=sys.stderr, flush=True)
                telemetry.get().instant(
                    "elastic:cross_axis_resume", step=start_step,
                    optimizer_sharding=live_stage, pipeline_degree=live_pp)
        if warm_thread is not None:
            # Join before the first dispatch: either the executable is
            # ready (the dispatch below hits the warm cache) or the warm
            # compile failed and the dispatch compiles cold — never both.
            warm_thread.join()
    flight = flightlib.get()
    flight.record("run_start", step=start_step, total_steps=int(total_steps),
                  degree=live_degree, model=config.model,
                  resumed=bool(start_step))
    if start_step:
        flight.record("restore", step=start_step)
    # Source is created here — after restore — so a real (streaming) pipeline
    # starts at the resume step rather than replaying from zero. A run with
    # no steps left skips pipeline construction entirely.
    source = (datalib.make_source(
        config, spec.input_kind, batch_shd, start_step=start_step,
        objective=spec.objective)
        if start_step < total_steps else None)
    # A resumed run may have fewer than warmup_steps left to execute (or
    # none at all, when the checkpoint already passed total_steps).
    warmup_steps = min(warmup_steps, max(total_steps - start_step - 1, 0))
    end_step = max(total_steps, start_step)

    if jax.process_index() == 0:
        # stderr so harness consumers (bench.py) keep a clean stdout
        ar = ("" if uses_gspmd(config, spec.input_kind)
              else f" | allreduce: {config.allreduce.describe()}")
        zl = getattr(train_step, "zero_layout", None)
        if zl is not None:
            _stage = getattr(train_step, "zero_stage", None) or "zero1"
            _ov = "+overlap" if getattr(train_step, "overlap", False) else ""
            _off = ("+offload" if getattr(config, "opt_state_offload", False)
                    else "")
            ar += f" | opt-sharding: {_stage}{_ov}{_off} ({zl.describe()})"
        if config.precision is not None:
            ar += f" | precision: {resolve_precision(config).describe()}"
        if getattr(config, "batch_ramp", None):
            ar += f" | batch-ramp: {config.batch_ramp}"
        print(f"# mesh: {meshlib.local_mesh_description(mesh)} | "
              f"model={config.model} global_batch={config.global_batch_size} "
              f"dtype={config.dtype} loader={resolved_loader}" + ar
              + (f" | resumed@{start_step}" if start_step else ""),
              file=sys.stderr, flush=True)

    # Periodic in-training eval (SURVEY.md §3.5: "train N epochs → periodic
    # eval → top-1"). eval_batches > 0 enables it; cadence is
    # config.eval_every_epochs converted to steps.
    evaluator = None
    eval_every_steps = 0
    evals: list[tuple[int, float]] = []
    # Under zero3 the live params are chunked; evaluation needs the full
    # model, so eval consumers go through the converter's cached gather
    # (identity below stage 3 / without sharding).
    _zconv = getattr(train_step, "zero_converter", None)

    def _eval_state(st):
        return _zconv.full_params_state(st) if _zconv is not None else st

    if eval_batches > 0:
        if spec.input_kind == "image":
            evaluator = _Evaluator(config, mesh, model, batch_shd,
                                   eval_batches)
        else:
            evaluator = _TokenEvaluator(config, spec, mesh, model, batch_shd,
                                        eval_batches, state)
        if config.eval_every_epochs > 0:
            spe = steps_per_epoch(config)
            if spe is not None:
                eval_every_steps = max(int(config.eval_every_epochs * spe), 1)
        if start_step < total_steps:
            # Overlap: warm-compile the eval step on a background thread
            # while the first training steps run, so the first
            # epoch-boundary eval doesn't stall the loop on a cold compile.
            evaluator.warm_compile_async(
                _eval_state(state), aot=getattr(train_step, "aot", None))

    # Fused multi-step blocks (config.steps_per_loop > 1): only when batches
    # are generated on-device (synthetic sources expose gen_fn) — a streaming
    # host pipeline needs a dispatch per step anyway. Blocks are split at
    # every step where host-side action fires (logging, checkpoint, eval,
    # warmup timer, profiling span edges, fault injection), so cadence
    # semantics are identical to the per-step path.
    fused_runner = None
    if config.steps_per_loop > 1 and source is not None:
        fused_runner = steps.make_fused_train_loop(
            train_step, source, batch_shd, mesh)
        if fused_runner is None and jax.process_index() == 0:
            print(f"# warning: steps_per_loop={config.steps_per_loop} ignored "
                  f"— loader {resolved_loader!r} streams from the host, so "
                  f"each step needs its own dispatch (fusion requires an "
                  f"on-device synthetic source)", file=sys.stderr, flush=True)

    def _next_boundary(pos: int) -> int:
        """Smallest action step (in completed-steps space) > pos."""
        cands = [total_steps]
        cadences = [config.log_every]
        if eval_every_steps:
            cadences.append(eval_every_steps)
        if ckpt is not None:
            cadences.append(config.checkpoint_every_steps)
        for c in cadences:
            if c > 0:
                cands.append((pos // c + 1) * c)
        points = [start_step + warmup_steps, *fault_plan.boundary_steps()]
        if config.profile_steps is not None:
            points.extend(config.profile_steps)
        if config.trace_steps is not None:
            # Fused blocks split at the telemetry window's edges, so its
            # step-tagged spans cover exactly the requested steps.
            points.extend(config.trace_steps)
        cands.extend(a for a in points if a is not None and a > pos)
        return min(c for c in cands if c > pos)

    # Preemption-aware checkpointing (SURVEY.md §5.3/5.4 extension): Cloud
    # TPU preemption delivers SIGTERM with a grace window, and the in-repo
    # launcher's fail-whole path does the same (_terminate_all). Instead of
    # losing everything since the last cadence save, note the signal and
    # save synchronously at the next step boundary, then exit nonzero so a
    # restart wrapper resumes from that exact step. Orbax saves are
    # collective, so this completes when every process got the signal
    # (whole-job preemption — the normal case); a partially-signaled job
    # falls back to the launcher's SIGKILL escalation, no worse than before.
    preempted: dict[str, Any] = {"signum": None}
    prev_sigterm = None
    install_handler = (ckpt is not None and threading.current_thread()
                       is threading.main_thread())
    if install_handler:
        def _on_sigterm(signum, frame):
            preempted["signum"] = signum
        prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)

    injector = faultslib.make_injector(fault_plan, ckpt,
                                       config.checkpoint_dir)
    bad_tracker = _BadStepTracker(config.bad_step_limit)
    # Online anomaly detection (observability/anomaly.py) over the chief's
    # log-cadence records: host-side medians only, so the cost is noise.
    # Flags become flight-recorder events + trace instants, and non-finite
    # signals feed bad_tracker so a diverged run still aborts when the
    # compiled guard is off.
    detector = (anomalylib.AnomalyDetector(
        straggler_ratio=(config.straggler_threshold
                         if config.straggler_threshold > 0 else 1.5))
        if getattr(config, "anomaly_detection", True)
        and jax.process_index() == 0 else None)
    mreg = metricslib.get()
    metrics = {}
    timed_examples = 0
    profile = _Profiler(config)
    # Phase telemetry (observability/telemetry.py; configured in run()):
    # host-side monotonic timestamps only — no device fetches on non-log
    # steps, and the disabled singleton makes record_span a single attribute
    # check. The heartbeat writer (observability/health.py) exists iff the
    # launcher exported DDL_HEARTBEAT_DIR; the straggler monitor
    # (observability/straggler.py) iff the job is multi-process.
    tele = telemetry.get()
    heartbeat = health.HeartbeatWriter.from_env()
    straggler = stragglib.make_monitor(config)
    phase_clock = tele.enabled or straggler is not None
    data_wait_acc = 0.0             # seconds in source.batch since last log
    data_wait_total = 0.0           # seconds in source.batch, whole run
    t_last_log = telemetry.now_s()  # log-interval origin for straggler math
    steps_at_last_log = start_step
    if heartbeat is not None:
        heartbeat.beat(start_step)  # arm the watchdog before compile
    # warmup_steps == 0 means "time everything" (incl. compile).
    t_timed = time.perf_counter() if warmup_steps == 0 else None
    # Cold-start measurement (docs/compile_cache.md): the first dispatch's
    # host-blocking wall time is the trace+compile (or AOT load) cost;
    # time_to_first_step_s is run() entry -> first step's results fetched.
    compile_time_s: Optional[float] = None
    time_to_first_step_s: Optional[float] = None
    compile_pending: Optional[float] = None
    overlap_frac: Optional[float] = None
    pipeline_bubble: Optional[float] = None
    reconfig_time_s: Optional[float] = None
    reconfig_phases: Optional[dict] = None
    try:
        i = start_step  # steps completed so far
        while i < total_steps:
            if preempted["signum"] is not None:
                tele.instant("preempted", step=i,
                             signum=preempted["signum"])
                flight.record("preempted", step=int(i),
                              signum=preempted["signum"])
                ckpt.maybe_save(i, state, force=True)
                ckpt.wait()
                raise SystemExit(
                    f"preempted (signal {preempted['signum']}): "
                    f"checkpoint saved at step {i}")
            if heartbeat is not None:
                # Rendezvous membership (launch.py --elastic): the launcher
                # raised the reform barrier — a host joined, announced a
                # drain, or was lost. Exit EXIT_DRAIN voluntarily at this
                # step boundary so the job re-forms WITHOUT any survivor
                # being torn down. A barrier at our own epoch (the one that
                # formed us) reads as None.
                barrier = health.poll_drain()
                if barrier is not None:
                    saved = False
                    if ckpt is not None and barrier.get("save", True):
                        # Every member is alive (the launcher only marks
                        # save-capable barriers when the membership is
                        # whole), so the collective save completes and the
                        # re-formed attempt resumes from THIS step instead
                        # of the last cadence save.
                        ckpt.maybe_save(i, state, force=True)
                        ckpt.wait()
                        saved = True
                    tele.instant("elastic:drain", step=int(i),
                                 epoch=barrier.get("epoch"))
                    flight.record("drain", step=int(i),
                                  epoch=barrier.get("epoch"),
                                  trigger=barrier.get("trigger"),
                                  saved=saved)
                    if jax.process_index() == 0:
                        print(f"# elastic: reform barrier (epoch "
                              f"{barrier.get('epoch')}, trigger "
                              f"{barrier.get('trigger')}) — draining at "
                              f"step {i}"
                              + (" after a collective save" if saved else
                                 " without saving (a member is already "
                                 "gone)"),
                              file=sys.stderr, flush=True)
                    raise SystemExit(health.EXIT_DRAIN)
            n = (min(config.steps_per_loop, _next_boundary(i) - i)
                 if fused_runner is not None else 1)
            profile.before_step(i)
            t_step0 = (time.perf_counter() if compile_time_s is None
                       else None)
            if n == 1:
                # The data-wait clock runs UNCONDITIONALLY (two monotonic
                # reads per step — noise): data_wait_frac must be present
                # on every log record even when ~0, so the anomaly
                # detector's loader-stall dominance test and the input-
                # pipeline headroom claim read the same always-on signal.
                t0 = telemetry.now_s()
                batch = source.batch(i)
                t1 = telemetry.now_s()
                data_wait_acc += t1 - t0
                if phase_clock:
                    tele.record_span("data_wait", t0, t1, step=i)
                    state, metrics = train_step(state, batch, rng)
                    tele.record_span("dispatch", t1, telemetry.now_s(),
                                     step=i)
                else:
                    state, metrics = train_step(state, batch, rng)
            else:
                if phase_clock:
                    t1 = telemetry.now_s()
                    state, metrics = fused_runner(state, rng, i, n)
                    tele.record_span("dispatch", t1, telemetry.now_s(),
                                     step=i, fused_steps=n)
                else:
                    state, metrics = fused_runner(state, rng, i, n)
            i += n
            if t_step0 is not None:
                # First step of this run. The dispatch above blocked the
                # host for the trace+compile (or AOT load); the fetch below
                # is a true execution barrier, so the pair gives cold-start
                # latency. One extra sync on step one only — numerics and
                # steady-state timing are untouched.
                compile_time_s = time.perf_counter() - t_step0
                t_fetch0 = time.perf_counter()
                jax.device_get(metrics)
                first_step_exec_s = time.perf_counter() - t_fetch0
                time_to_first_step_s = time.perf_counter() - t_origin
                compile_pending = compile_time_s
                tele.gauge("compile_time_s", round(compile_time_s, 3),
                           step=int(i))
                tele.gauge("time_to_first_step_s",
                           round(time_to_first_step_s, 3), step=int(i))
                if elastic_event is not None and isinstance(
                        elastic_event.get("detect_t"), (int, float)):
                    # Reconfiguration span: launcher-side fault detection ->
                    # this first post-resume step, both ends on the shared
                    # local CLOCK_MONOTONIC. Covers teardown, relaunch,
                    # restore, and recompile — the operator-visible outage.
                    detect_t = float(elastic_event["detect_t"])
                    reconfig_time_s = telemetry.now_s() - detect_t
                    tele.gauge("reconfiguration_time_s",
                               round(reconfig_time_s, 3), step=int(i))
                    # Phase breakdown of the outage (all on the shared
                    # CLOCK_MONOTONIC): detect -> last member drained
                    # (launcher clock), restore (orbax wall time), compile
                    # (first dispatch host-block — near zero when the warm
                    # overlap landed), first-step execution; spawn_s is the
                    # remainder (relaunch + imports + device init). With
                    # the restore/compile overlap the parts can overlap in
                    # wall time, so they need not sum to total_s.
                    drain_done = elastic_event.get("drain_done_t")
                    drain_s = (max(0.0, float(drain_done) - detect_t)
                               if isinstance(drain_done, (int, float))
                               else None)
                    restore_s = (ckpt.last_restore_s
                                 if ckpt is not None else None)
                    known = sum(v for v in (drain_s, restore_s,
                                            compile_time_s,
                                            first_step_exec_s)
                                if v is not None)
                    reconfig_phases = {
                        "total_s": round(reconfig_time_s, 3),
                        "drain_s": (round(drain_s, 3)
                                    if drain_s is not None else None),
                        "restore_s": (round(restore_s, 3)
                                      if restore_s is not None else None),
                        "compile_s": round(compile_time_s, 3),
                        "first_step_s": round(first_step_exec_s, 3),
                        "spawn_s": round(
                            max(0.0, reconfig_time_s - known), 3),
                    }
                    for k, v in reconfig_phases.items():
                        if k != "total_s" and v is not None:
                            tele.gauge(f"reconfiguration_{k}", v,
                                       step=int(i))
                    # The outage span, closed: the launcher recorded the
                    # re-formation *plan*; this records it *landed*.
                    flight.record(
                        "reconfiguration", step=int(i),
                        trigger=elastic_event.get("trigger"),
                        degree_before=elastic_event.get("degree_before"),
                        degree_after=elastic_event.get("degree_after"),
                        epoch=elastic_event.get("epoch"),
                        reconfiguration_time_s=round(reconfig_time_s, 3),
                        phases=reconfig_phases,
                        resume_step=start_step)
                if tele.enabled and getattr(train_step, "zero_stage", None):
                    # Backward/collective overlap gauge: fraction of the
                    # step's reduce-scatter spans issued INSIDE backward
                    # (the custom_vjp bucket boundaries mark theirs
                    # overlapped=True). Spans are trace-time, so an AOT
                    # cache hit (zero retraces) leaves no spans and the
                    # gauge honestly reads 0 — docs/zero_sharding.md.
                    overlap_frac = telemetry.overlap_fraction(
                        tele.snapshot())
                    tele.gauge("backward_collective_overlap",
                               round(overlap_frac, 4), step=int(i))
                if tele.enabled and config.parallel.pipeline > 1:
                    # Measured pipeline bubble: idle/total stage-ticks from
                    # the per-tick `pipeline_tick` instants the schedule
                    # emits at trace time. Like the overlap gauge these are
                    # trace-time events, so an AOT cache hit leaves none —
                    # the helper returns None then (not a fake 0.0) and the
                    # gauge is simply skipped. docs/pipeline.md has the
                    # analytic curve this is compared against in bench.
                    pipeline_bubble = telemetry.pipeline_bubble_fraction(
                        tele.snapshot())
                    if pipeline_bubble is not None:
                        tele.gauge("pipeline_bubble_fraction",
                                   round(pipeline_bubble, 4), step=int(i))
            profile.after_step(i - 1, metrics)
            bad_tracker.push(metrics)
            done = i - start_step
            if done == warmup_steps:
                # device_get, not block_until_ready: a fetch is a true
                # execution barrier on every backend (remote-tunneled devices
                # can report buffers "ready" while programs are still in
                # flight, which would start the timing window early).
                jax.device_get(metrics)
                t_timed = time.perf_counter()
            if i % config.log_every == 0 or i == total_steps:
                extra = {}
                t_log = telemetry.now_s()
                interval_steps = max(i - steps_at_last_log, 1)
                if straggler is not None:
                    # One small allgather per log step, on EVERY process at
                    # the same step — a collective, like the eval syncs.
                    # compile_s rides along exactly once (the first log
                    # after the program was built — the same step on every
                    # host), surfacing compile stragglers.
                    extra = straggler.collect(
                        int(i), (t_log - t_last_log) / interval_steps,
                        data_wait_acc / interval_steps,
                        compile_s=compile_pending)
                if compile_pending is not None:
                    extra["compile_time_s"] = round(compile_pending, 3)
                    extra["time_to_first_step_s"] = round(
                        time_to_first_step_s, 3)
                    compile_pending = None
                # Always-present loader-stall share of the interval (0.0
                # when the pipeline kept up — fused on-device blocks fetch
                # nothing and honestly read 0). The logger mirrors every
                # numeric field into telemetry gauges, so this lands in
                # the JSONL record, the gauge stream, and the registry.
                extra["data_wait_frac"] = round(
                    data_wait_acc / (t_log - t_last_log), 6) \
                    if t_log - t_last_log > 1e-9 else 0.0
                # logger floats every metric (a true fetch barrier); no
                # separate block needed. Its span is therefore the device
                # time of the steps still in flight — log-cadence only, so
                # telemetry adds no fetch of its own.
                with tele.span("fetch_barrier", step=int(i)):
                    # now_s=t_log: the logger's step-time window uses the
                    # SAME clock reading the straggler skew math above
                    # used — one timestamp per log step, not two
                    # (utils/logging.py mirrors the record into telemetry
                    # gauges, closing the duplicated emit path).
                    log_rec = logger.log(
                        int(i), metrics,
                        examples_per_step=config.global_batch_size,
                        now_s=t_log,
                        lr=float(sched(i - 1)), **extra)
                flight.record("step", step=int(i),
                              loss=log_rec.get("loss"),
                              examples_per_sec=log_rec.get(
                                  "examples_per_sec"))
                if jax.process_index() == 0:
                    _observe_and_detect(log_rec, int(i), mreg, detector,
                                        flight, tele, bad_tracker,
                                        overlap_frac=overlap_frac,
                                        pipeline_bubble=pipeline_bubble,
                                        data_wait_s=data_wait_acc,
                                        interval_s=t_log - t_last_log)
                if heartbeat is not None:
                    heartbeat.beat(int(i))
                if tele.enabled:
                    _record_hbm_gauges(tele, int(i))
                t_last_log, steps_at_last_log = telemetry.now_s(), i
                data_wait_total += data_wait_acc
                data_wait_acc = 0.0
            if done > warmup_steps:
                # Blocks never straddle the warmup edge (it is a boundary),
                # so the whole block counts toward the timed window.
                timed_examples += config.global_batch_size * n
            if ckpt is not None:
                t_ck = telemetry.now_s() if tele.enabled else 0.0
                if ckpt.maybe_save(i, state):
                    # Recorded only when a save actually launched (async:
                    # the span is the launch + state-gather cost, not the
                    # full write).
                    if tele.enabled:
                        tele.record_span("checkpoint_save", t_ck,
                                         telemetry.now_s(), step=int(i))
                    flight.record("save", step=int(i))
            if (eval_every_steps and i % eval_every_steps == 0
                    and i < total_steps):
                t_eval = time.perf_counter()
                with tele.span("eval", step=int(i)):
                    val = evaluator(_eval_state(state))
                evals.append((i, val))
                logger.log(int(i), {evaluator.metric_name: val})
                if t_timed is not None:
                    # Keep throughput numbers about training: shift the
                    # timing origin past the eval pause.
                    t_timed += time.perf_counter() - t_eval
            if injector is not None:
                # Scheduled fault injection (SURVEY.md §5.3, robustness/
                # faults.py): crash/sigterm/sigkill/corrupt after completing
                # step i — AFTER maybe_save, so a cadence save at i is
                # already (async-)launched when the fault lands, exactly the
                # race a real preemption exposes.
                injector(i)
        # End-of-run sync: fetching the final step's metrics and step counter
        # is a true completion barrier for the whole dispatch queue (the last
        # program's outputs exist only after it ran), without a per-leaf
        # readiness walk over the params/opt-state tree — which on a
        # remote-tunneled device costs seconds and would pollute timing.
        jax.device_get((metrics, state.step))
        bad_tracker.drain()
    finally:
        # prev may be None when the prior handler was installed from C (not
        # visible to Python) — restoring None would raise inside finally and
        # mask the propagating exception; SIG_DFL is the honest fallback.
        if install_handler:
            signal.signal(signal.SIGTERM,
                          prev_sigterm if prev_sigterm is not None
                          else signal.SIG_DFL)
        profile.finish()
    if ckpt is not None:
        if total_steps > start_step:
            if ckpt.maybe_save(total_steps, state, force=True):
                flight.record("save", step=int(total_steps), final=True)
        ckpt.wait()

    summary: dict[str, Any] = {
        "final_step": end_step,
        "start_step": start_step,
        "final_metrics": {k: float(v) for k, v in metrics.items()},
        "bad_steps": bad_tracker.total,
    }
    if compile_time_s is not None:
        summary["compile_time_s"] = round(compile_time_s, 3)
        summary["time_to_first_step_s"] = round(time_to_first_step_s, 3)
    if elastic_event is not None:
        summary["elastic_event"] = {
            k: elastic_event.get(k)
            for k in ("trigger", "degree_before", "degree_after", "epoch")}
        if reconfig_time_s is not None:
            summary["reconfiguration_time_s"] = round(reconfig_time_s, 3)
        if reconfig_phases is not None:
            summary["reconfiguration_phases"] = reconfig_phases
        _write_elastic_sidecar(elastic_event, reconfig_time_s, start_step,
                               phases=reconfig_phases)
    if getattr(train_step, "zero_stage", None) is not None:
        summary["optimizer_sharding"] = {
            "stage": train_step.zero_stage,
            "overlap": bool(getattr(train_step, "overlap", False)),
            "overlap_fraction": overlap_frac,
        }
    if config.parallel.pipeline > 1:
        summary["pipeline"] = {
            "schedule": config.pipeline_schedule,
            "virtual_stages": config.pipeline_virtual_stages,
            "bubble_fraction": pipeline_bubble,
        }
    _write_sharding_sidecar(config, train_step, overlap_frac,
                            pipeline_bubble)
    aot = getattr(train_step, "aot", None)
    if aot is not None and aot.enabled:
        summary["compile_cache"] = aot.stats()
        aot.flush_stats()  # counters land next to the cache for doctor.py
    hbm = _device_memory_stats(state, train_step)
    if hbm:
        summary["memory"] = hbm
        if jax.process_index() == 0:
            for k in ("resident_bytes_per_device", "peak_bytes_in_use"):
                if k in hbm:
                    mreg.observe(k, hbm[k], step=end_step)
            parts = []
            if "peak_bytes_in_use" in hbm:
                parts.append(
                    f"peak_hbm={hbm['peak_bytes_in_use'] / 2**20:.1f}MiB")
            for k in ("params_bytes_per_device",
                      "grads_bytes_per_device",
                      "opt_state_bytes_per_device",
                      "ema_params_bytes_per_device",
                      "resident_bytes_per_device"):
                if k in hbm:
                    parts.append(f"{k.split('_bytes')[0]}/dev="
                                 f"{hbm[k] / 2**20:.2f}MiB")
            if parts:
                print("# memory: " + " ".join(parts),
                      file=sys.stderr, flush=True)
    # Input-pipeline headroom (docs/perf_measurement.md): whole-run seconds
    # spent blocked in source.batch, and — when a timed window exists — the
    # share of that window they represent. ~0 means the loader kept ahead
    # of the device at this batch size; the large-batch claim ("still ~0
    # at 2x the batch") reads THIS field off the stamped record.
    data_wait_total += data_wait_acc
    summary["input_pipeline"] = {
        "loader": resolved_loader,
        "prefetch_depth": int(datalib.effective_prefetch_depth(config)),
        "data_wait_s": round(data_wait_total, 4),
    }
    if t_timed is not None and timed_examples:
        elapsed = time.perf_counter() - t_timed
        summary["examples_per_sec"] = timed_examples / elapsed
        summary["examples_per_sec_per_chip"] = (
            summary["examples_per_sec"] / jax.device_count())
        summary["steps_per_sec"] = (
            total_steps - start_step - warmup_steps) / elapsed
        if elapsed > 1e-9:
            # Approximate on purpose: the wait accumulator spans the whole
            # run while the clock window excludes warmup — headroom is a
            # capacity signal, not a benchmark metric.
            summary["input_pipeline"]["data_wait_frac"] = round(
                min(data_wait_total / elapsed, 1.0), 6)
    # Run summaries emit into the perf_report schema: this summary was
    # measured by THIS process on the backend below — provenance fresh —
    # and carries the roofline %-of-peak (null when model FLOPs or the
    # chip's spec peak are unknown: the field must exist on every summary,
    # not only the lucky ones).
    from distributeddeeplearning_tpu.observability import perf_report
    summary["pct_of_peak"] = perf_report.roofline(
        summary.get("examples_per_sec_per_chip"), config.model,
        seq_len=config.data.seq_len,
        mlm_positions=(resolve_mlm_max_predictions(
            config.data.mlm_max_predictions, config.data.seq_len,
            spec.objective) if spec.input_kind == "tokens" else 0),
        device_kind=getattr(jax.devices()[0], "device_kind", None),
        compute_dtype=resolve_precision(config).compute_dtype,
    ).get("pct_of_peak")
    perf_report.annotate(summary, provenance="fresh",
                         config=config, total_steps=total_steps)
    if evaluator is not None:
        final_val = evaluator(_eval_state(state))
        evals.append((end_step, final_val))
        summary[evaluator.metric_name] = final_val
        best = evaluator.best(t for _, t in evals)
        summary["best_" + evaluator.metric_name.removeprefix("eval_")] = best
        summary["evals"] = evals
        if evaluator.metric_name == "eval_loss":
            import math

            summary["eval_ppl"] = math.exp(min(final_val, 30.0))
    if return_state:
        summary["state"] = state
    flight.record("run_end", step=end_step, bad_steps=bad_tracker.total)
    if flight.enabled and jax.process_index() == 0:
        # Final metrics export next to the flight record — the aggregate
        # snapshot a post-mortem (or a textfile scraper) picks up.
        mreg.write_prometheus(os.path.join(flight.directory, "metrics.prom"))
        mreg.write_snapshot(
            os.path.join(flight.directory, "metrics_snapshot.json"))
    return summary


class _BadStepTracker:
    """Host-side circuit breaker over the compiled step's ``bad_step`` flag.

    The guard in train/steps.py skips non-finite updates on-device; this
    tracker counts the skips and aborts the run after ``limit`` CONSECUTIVE
    skips (a run whose every step is bad is diverged, not unlucky). Flags
    are fetched LAGGED — a flag is only ``float()``-ed once two newer steps
    have been dispatched, by which time its program has executed — so the
    breaker never synchronizes the async dispatch pipeline; the remainder
    drains at end of run. Fused multi-step blocks report their last step's
    flag only, so under ``steps_per_loop`` the count is per-block (blocks
    split at injected-fault boundaries, keeping chaos tests exact).
    """

    _LAG = 2

    def __init__(self, limit: int):
        self.limit = max(int(limit), 1)
        self.total = 0
        self._consecutive = 0
        self._window: list = []

    def push(self, metrics) -> None:
        flag = metrics.get("bad_step")
        if flag is None:
            return
        self._window.append(flag)
        if len(self._window) > self._LAG:
            self._check(self._window.pop(0))

    def drain(self) -> None:
        while self._window:
            self._check(self._window.pop(0))

    def note_anomaly(self) -> None:
        """Anomaly-detector feed (observability/anomaly.py): a non-finite
        loss/grad signal on the log cadence counts like a bad-step skip,
        so a run pinned at NaN aborts through the SAME breaker even when
        the compiled guard was never built into the step."""
        self._bump()

    def _check(self, flag) -> None:
        if float(jax.device_get(flag)) > 0:
            self._bump()
        else:
            self._consecutive = 0

    def _bump(self) -> None:
        self.total += 1
        self._consecutive += 1
        if self._consecutive >= self.limit:
            raise RuntimeError(
                f"aborting: {self._consecutive} consecutive non-finite "
                f"update steps (bad_step_limit={self.limit}) — the run "
                f"is diverging, not hitting stray bad batches; lower "
                f"the learning rate or inspect the data shards. "
                f"{self.total} update(s) were skipped in total.")


def _observe_and_detect(log_rec, step, mreg, detector, flight, tele,
                        bad_tracker, *, overlap_frac, pipeline_bubble=None,
                        data_wait_s, interval_s) -> None:
    """Chief-side log-cadence fan-out: feed the metrics registry and the
    anomaly detector from the record ``MetricLogger.log`` just built.

    The straggler monitor's per-host fields ride inside ``log_rec`` (they
    were passed to ``log`` as extras), so host skew needs no second
    allgather here. The registry export refreshes every log step when a
    flight dir exists — cheap (two small atomic writes) and it means a
    killed run leaves a current snapshot, not just a final one.
    """
    mreg.observe_many(log_rec, step=step)
    if overlap_frac is not None:
        mreg.observe("backward_collective_overlap", overlap_frac, step=step)
    if pipeline_bubble is not None:
        mreg.observe("pipeline_bubble_fraction", pipeline_bubble, step=step)
    skew = None
    if log_rec.get("host_step_time_mean"):
        skew = (log_rec.get("host_step_time_max", 0.0)
                / log_rec["host_step_time_mean"])
        mreg.observe("host_step_time_skew", skew, step=step)
    if detector is not None:
        wait_frac = (data_wait_s / interval_s) if interval_s > 1e-9 else None
        anomalies = detector.update(
            step, loss=log_rec.get("loss"),
            grad_norm=log_rec.get("grad_norm"),
            examples_per_sec=log_rec.get("examples_per_sec"),
            data_wait_frac=wait_frac, straggler_ratio=skew,
            bad_step=log_rec.get("bad_step"))
        anomalylib.report(anomalies, flight_rec=flight, tele=tele,
                          bad_tracker=bad_tracker)
    if flight.enabled:
        mreg.write_prometheus(os.path.join(flight.directory, "metrics.prom"))
        mreg.write_snapshot(
            os.path.join(flight.directory, "metrics_snapshot.json"))


def _record_hbm_gauges(tele, step: int) -> None:
    """Periodic HBM telemetry (log cadence, telemetry on): allocator stats
    straight from ``memory_stats()`` — host-side bookkeeping, no device
    fetch. Backends without allocator stats (CPU) record nothing."""
    try:
        for d, dev in enumerate(jax.local_devices()):
            stats = dev.memory_stats() or {}
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                if key in stats:
                    tele.gauge(f"hbm_{key}/d{d}", int(stats[key]), step=step)
    except Exception:
        pass


def _sharding_sidecar_path() -> str:
    # Indirection kept monkeypatchable (tests redirect it off-repo); the
    # write itself goes through the shared helper (observability/sidecars).
    return sidecars.path_for("last_run_sharding")


def _write_sharding_sidecar(config, train_step, overlap_frac,
                            pipeline_bubble=None) -> None:
    """Record the run's active sharding stage + overlap status where
    tools/doctor.py looks (best-effort, like the compile-cache stats)."""
    if jax.process_index() != 0:
        return
    rec = {
        "optimizer_sharding": config.optimizer_sharding,
        "overlap_collectives": bool(
            getattr(config, "overlap_collectives", True)),
        "overlap": bool(getattr(train_step, "overlap", False)),
        "overlap_fraction": overlap_frac,
        "opt_state_offload": bool(
            getattr(config, "opt_state_offload", False)),
        "dp": config.parallel.data * config.parallel.fsdp,
        "model": config.model,
        # Active precision policy + ramp, for tools/doctor.py check_precision
        # — which policy actually ran, not which one the flags implied.
        "precision": resolve_precision(config).describe(),
        "precision_explicit": config.precision is not None,
        "batch_ramp": optim.ramp_describe(config),
    }
    if config.parallel.pipeline > 1:
        # Pipeline block for tools/doctor.py check_pipeline: what schedule
        # the run used and the bubble it measured (None on AOT warm boots
        # where no trace-time tick instants existed to measure from).
        rec["pipeline"] = {
            "stages": config.parallel.pipeline,
            "schedule": config.pipeline_schedule,
            "virtual_stages": config.pipeline_virtual_stages,
            "bubble_fraction": pipeline_bubble,
        }
    sidecars.write(_sharding_sidecar_path(), rec)


def _elastic_sidecar_path() -> str:
    return sidecars.path_for("last_elastic_event")


def _write_elastic_sidecar(event, reconfig_time_s, resume_step,
                           phases=None) -> None:
    """Record the re-formation this attempt resumed under where
    tools/doctor.py looks (best-effort, like the sharding sidecar)."""
    if jax.process_index() != 0:
        return
    sidecars.write(_elastic_sidecar_path(), {
        "trigger": event.get("trigger"),
        "degree_before": event.get("degree_before"),
        "degree_after": event.get("degree_after"),
        "epoch": event.get("epoch"),
        "reconfiguration_time_s": (round(reconfig_time_s, 3)
                                   if reconfig_time_s is not None
                                   else None),
        "phases": phases,
        "resume_step": int(resume_step),
    })


def _device_memory_stats(state=None, train_step=None) -> Optional[dict]:
    """Peak/current HBM on local device 0 (where the backend reports it;
    CPU doesn't) plus — given the final ``state`` — the per-device resident
    bytes of params / optimizer state / EMA, computed from the arrays'
    actual shard placement, and — given the ``train_step`` — the MODELED
    per-device gradient bytes (zero.modeled_grad_bytes: gradients are
    transient, so residency is a schedule property, not a measurement).
    ``resident_bytes_per_device`` sums the components into the per-device
    memory-ladder number the ZeRO acceptance test asserts decreases
    replicated→zero1→zero2→zero3. The state breakdown works on EVERY
    backend, so the win is measurable even on the CPU/fake-device path
    where allocator peaks are unavailable. The observability counterpart
    of nvidia-smi in the reference's stack."""
    out: dict = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            out[key] = int(stats[key])
    if state is not None:
        try:
            dev = jax.local_devices()[0]
            for name, tree in (("params", state.params),
                               ("opt_state", state.opt_state),
                               ("ema_params", state.ema_params)):
                if tree is not None:
                    out[f"{name}_bytes_per_device"] = (
                        statelib.resident_bytes(tree, dev))
        except Exception:
            pass
    gb = getattr(train_step, "grad_bytes_per_device", None)
    if gb is not None:
        out["grads_bytes_per_device"] = int(gb)
    resident = [out.get(k) for k in ("params_bytes_per_device",
                                     "grads_bytes_per_device",
                                     "opt_state_bytes_per_device",
                                     "ema_params_bytes_per_device")]
    if any(v is not None for v in resident):
        out["resident_bytes_per_device"] = sum(v or 0 for v in resident)
    return out or None


class _Profiler:
    """Hot-loop tracing hook (SURVEY.md §5.1) — the TPU replacement for
    Horovod's HOROVOD_TIMELINE Chrome trace. ``config.profile_steps=(a, b)``
    captures a ``jax.profiler`` trace of steps [a, b) into
    ``config.profile_dir`` (TensorBoard-loadable), process 0 only."""

    def __init__(self, config: TrainConfig):
        self.span = config.profile_steps
        self.dir = config.profile_dir or "/tmp/ddl_tpu_profile"
        self.active = False
        self.enabled = self.span is not None and jax.process_index() == 0

    def before_step(self, step: int) -> None:
        if not self.enabled:
            return
        lo, hi = self.span
        if not self.active and lo <= step < hi:
            jax.profiler.start_trace(self.dir)
            self.active = True

    def after_step(self, step: int, metrics) -> None:
        # Stop only after the last profiled step's device work completes —
        # dispatch is async, so stopping without blocking would trace host
        # activity only.
        if self.active and step + 1 >= self.span[1]:
            jax.block_until_ready(metrics)
            self._stop()

    def finish(self) -> None:
        if self.active:
            self._stop()

    def _stop(self) -> None:
        jax.profiler.stop_trace()
        self.active = False
        print(f"# profiler trace written to {self.dir}",
              file=sys.stderr, flush=True)


class _EvaluatorBase:
    """Shared held-out-eval plumbing (SURVEY.md §3.5).

    Built once per run — the compiled eval step is reused across every
    periodic (epoch-boundary) and final invocation. The synthetic source is
    indexable and reused, evaluating at a fixed huge batch-index offset
    (``SYNTHETIC_EVAL_OFFSET``) disjoint from any training step index, so
    eval batches never replay training batches and every eval scores the
    same held-out set. A real validation split is a *finite ordered
    stream*, so a fresh source is built per invocation (each eval reads the
    split from its start) with prefetch_depth=0 — construction must not
    eagerly decode lookahead batches a short eval would throw away.

    Subclasses set ``metric_name``/``best``, build ``self.eval_step``, and
    implement ``_accumulate`` over the per-batch eval-step outputs.
    """

    SYNTHETIC_EVAL_OFFSET = 1 << 30
    input_kind: str
    objective: str = "classify"

    def __init__(self, config: TrainConfig, batch_shd, num_batches: int):
        self.num_batches = num_batches
        self.synthetic = config.data.synthetic or not config.data.data_dir
        self._config, self._batch_shd = config, batch_shd
        self._synth_source = (
            datalib.make_source(config, self.input_kind, batch_shd,
                                objective=self.objective)
            if self.synthetic else None)
        self._warm_thread: Optional[threading.Thread] = None
        self._warm_exec = None

    def warm_compile_async(self, state, aot=None) -> None:
        """Compile the eval step on a background thread while the first
        training steps run (overlap — the loop's hot path never blocks on
        this). The executable is built ahead-of-time from abstract avals
        (``lower().compile()``): the live ``state`` buffers are donated by
        the next train step, so only their ShapeDtypeStructs are captured.
        The first eval joins the thread and calls the prepared executable;
        any failure here silently leaves the cold path in place.

        ``aot`` (perf/aot.StepExecutableCache) additionally persists the
        executable, so the next launch of this config skips even the
        overlapped compile.
        """
        if self._warm_thread is not None or self._warm_exec is not None:
            return
        if state.ema_params is not None:
            state = state.replace(params=state.ema_params)
        state_struct = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), state)
        tele = telemetry.get()

        def work():
            try:
                t0 = telemetry.now_s()
                # One throwaway batch fixes the eval batch avals; synthetic
                # sources are indexable (nothing is consumed) and real
                # sources are rebuilt fresh per eval invocation anyway.
                source, offset = self._source_and_offset()
                batch = source.batch(offset)
                fn = None
                key = None
                if aot is not None and aot.enabled:
                    key = aot.key("eval_step", (state_struct, batch))
                    fn = aot.load("eval_step", key)
                if fn is None:
                    lower = getattr(self.eval_step, "lower_for",
                                    None) or self.eval_step.lower
                    fn = lower(state_struct, batch).compile()
                    if key is not None:
                        aot.save("eval_step", key, fn)
                self._warm_exec = fn
                tele.record_span("warm_compile", t0, telemetry.now_s())
            except Exception:  # noqa: BLE001 - warm-up is optional
                self._warm_exec = None

        self._warm_thread = threading.Thread(
            target=work, daemon=True, name="ddl-eval-warm-compile")
        self._warm_thread.start()

    def _eval_fn(self):
        """The step callable for this invocation: the warm-compiled
        executable when the overlap produced one, else the cold jit."""
        if self._warm_thread is not None:
            self._warm_thread.join()
            self._warm_thread = None
        return self._warm_exec if self._warm_exec is not None \
            else self.eval_step

    def _source_and_offset(self):
        if self.synthetic:
            return self._synth_source, self.SYNTHETIC_EVAL_OFFSET
        import dataclasses
        cfg = self._config.replace(data=dataclasses.replace(
            self._config.data, prefetch_depth=0))
        return datalib.make_source(
            cfg, self.input_kind, self._batch_shd, train=False,
            objective=self.objective), 0

    def __call__(self, state) -> float:
        if state.ema_params is not None:
            # EMA evaluation: score the shadow weights (the reason the
            # EMA exists); training params continue unaffected.
            state = state.replace(params=state.ema_params)
        source, offset = self._source_and_offset()
        # Multi-process: the exhaustion decision must be GLOBAL — eval
        # steps are cross-process collectives, so one process breaking
        # while another proceeds would deadlock the job. When the source
        # can size itself up front (``batches_hint`` — the imagefolder val
        # splits of all three loaders), the processes agree ONCE on
        # min(local hints) before the loop (ADVICE r4: one collective, not
        # one per batch); otherwise every iteration carries the per-batch
        # agreement below.
        num_batches = self.num_batches
        per_batch_sync = jax.process_count() > 1
        if per_batch_sync:
            hint = getattr(source, "batches_hint", None)
            if hint is not None:
                import numpy as np
                from jax.experimental import multihost_utils

                hints = multihost_utils.process_allgather(
                    np.asarray([hint], np.int64))
                num_batches = min(num_batches, int(hints.min()))
                per_batch_sync = False
                if num_batches < self.num_batches:
                    import warnings

                    warnings.warn(
                        f"validation split holds {num_batches} of the "
                        f"{self.num_batches} requested eval batches; "
                        f"scoring the available ones")
                if num_batches == 0:
                    raise RuntimeError(
                        f"validation split yields no full batch on some "
                        f"process (global batch "
                        f"{self._config.global_batch_size}); shrink the "
                        f"batch or provide more validation images")
        outs = []
        eval_fn = self._eval_fn()
        for j in range(num_batches):
            try:
                batch = source.batch(offset + j)
            except StopIteration:
                if not per_batch_sync and jax.process_count() > 1:
                    # The upfront agreement promised this batch existed;
                    # running dry here means the hint was wrong, and a
                    # silent per-process break would deadlock the
                    # collective eval step on the others. Die loudly.
                    raise RuntimeError(
                        f"eval source exhausted at batch {j} despite "
                        f"batches_hint promising {num_batches}; the "
                        f"loader's sharding and its hint disagree")
                batch = None
            # Per-batch agreement (unknown-size streams): if ANY shard ran
            # dry (imagefolder files rarely divide evenly), all stop here
            # and the fetched batches of the others are discarded.
            if per_batch_sync:
                import numpy as np
                from jax.experimental import multihost_utils

                have = multihost_utils.process_allgather(
                    np.asarray([batch is not None], np.int32))
                if not have.all():
                    batch = None
            if batch is None:
                # A real validation split is finite; a short one must yield
                # a result over what exists, not a crash mid-training.
                if not outs:
                    raise RuntimeError(
                        f"validation split yielded no full batch (global "
                        f"batch {self._config.global_batch_size}); shrink "
                        f"the batch or provide more validation images")
                import warnings

                warnings.warn(
                    f"validation split exhausted after {j} of "
                    f"{self.num_batches} eval batches; scoring the "
                    f"available ones")
                break
            try:
                outs.append(jax.device_get(eval_fn(state, batch)))
            except Exception:  # noqa: BLE001
                if eval_fn is self.eval_step:
                    raise
                # The warm executable's avals disagree with the live batch
                # (e.g. a real loader emitted a different structure than
                # the warm-up batch). Eval steps don't donate, so retrying
                # through the cold jit is safe.
                eval_fn = self.eval_step
                self._warm_exec = None
                outs.append(jax.device_get(eval_fn(state, batch)))
        return self._accumulate(outs)


class _TokenEvaluator(_EvaluatorBase):
    """Held-out LM eval for token models: mean per-token loss over
    ``num_batches`` (perplexity = exp(loss)), computed with dropout off and
    exact (loss_sum, token_count) aggregation — identical to a
    single-device pass under any sharding. ``best`` is ``min``."""

    metric_name = "eval_loss"
    best = staticmethod(min)
    input_kind = "tokens"

    def __init__(self, config: TrainConfig, spec, mesh, model, batch_shd,
                 num_batches: int, state):
        self.objective = spec.objective
        super().__init__(config, batch_shd, num_batches)
        shardings = jax.tree_util.tree_map(lambda x: x.sharding, state)
        self.eval_step = steps.make_token_eval_step(
            model, mesh, config, shardings, spec.objective)

    def _accumulate(self, outs) -> float:
        loss_sum = count = 0.0
        for out in outs:
            loss_sum += float(out["loss_sum"])
            count += float(out["count"])
        return loss_sum / max(count, 1.0)


class _Evaluator(_EvaluatorBase):
    """Sharded top-1 over ``num_batches``: per-shard correct counts are
    psummed across the DP axes before dividing, so the result is identical
    to a single-device pass over the global batch."""

    metric_name = "eval_top1"
    best = staticmethod(max)
    input_kind = "image"

    def __init__(self, config: TrainConfig, mesh, model, batch_shd,
                 num_batches: int):
        super().__init__(config, batch_shd, num_batches)
        self.eval_step = steps.make_dp_eval_step(model, mesh, config)

    def _accumulate(self, outs) -> float:
        correct = total = 0
        for out in outs:
            correct += int(out["correct"])
            total += int(out["total"])
        return correct / max(total, 1)
