"""The model-agnostic training loop behind ``train.py``.

One loop serves every acceptance config (BASELINE.json:6-12): it selects the
parallel execution style (explicit-collective DP for CNNs, GSPMD for
transformer workloads with tp/sp), builds the data source, and drives the
compiled step with JSONL metrics — the role the reference's per-framework
``src/train-script.py`` files played (SURVEY.md §2 #1-#3), minus the
framework forks.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data import synthetic
from distributeddeeplearning_tpu.models import model_spec
from distributeddeeplearning_tpu.parallel import mesh as meshlib
from distributeddeeplearning_tpu.parallel import sharding as shardlib
from distributeddeeplearning_tpu.train import optim, steps
from distributeddeeplearning_tpu.train.state import TrainState
from distributeddeeplearning_tpu.utils.logging import MetricLogger


def _dtype(config: TrainConfig):
    return jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32


def uses_gspmd(config: TrainConfig, input_kind: str) -> bool:
    """Transformers (or any config with tp/sp/fsdp axes) take the GSPMD path;
    pure-DP CNNs take the explicit shard_map+psum path."""
    p = config.parallel
    return input_kind == "tokens" or p.model > 1 or p.seq > 1 or p.fsdp > 1


def build(config: TrainConfig, total_steps: int):
    """Construct (mesh, model, source, state, train_step, meta) for a config."""
    spec = model_spec(config.model)
    _ = config.per_device_batch  # early, friendly divisibility error
    mesh = meshlib.make_mesh(config.parallel)
    dtype = _dtype(config)
    if spec.input_kind == "tokens":
        model = spec.build(vocab_size=config.data.vocab_size, dtype=dtype)
    else:
        model = spec.build(num_classes=config.data.num_classes, dtype=dtype)

    tx, sched = optim.make_optimizer(
        config.optimizer, config.global_batch_size, total_steps,
        config.steps_per_epoch)
    rng = jax.random.key(config.seed)

    seq_dim = 1 if spec.input_kind == "tokens" else None
    batch_shd = shardlib.batch_sharding(mesh, seq_dim=seq_dim)
    source = synthetic.make_source(config, spec.input_kind, sharding=batch_shd)

    if uses_gspmd(config, spec.input_kind):
        example = source.batch(0)
        state, shardings = steps.init_sharded_state(
            model, tx, mesh, config, example, rng, spec.input_kind)
        train_step = steps.make_gspmd_train_step(
            model, tx, mesh, config, shardings, spec.input_kind)
    else:
        def init_fn(rng):
            if spec.input_kind == "tokens":
                variables = model.init(
                    {"params": rng, "dropout": rng},
                    jnp.zeros((1, config.data.seq_len), jnp.int32),
                    train=False)
            else:
                size = config.data.image_size
                variables = model.init(
                    {"params": rng}, jnp.zeros((1, size, size, 3), dtype),
                    train=False)
            params = variables["params"]
            return TrainState.create(
                params=params, opt_state=tx.init(params),
                batch_stats=variables.get("batch_stats"))

        replicated = shardlib.replicated(mesh)
        state = jax.jit(init_fn, out_shardings=replicated)(rng)
        train_step = steps.make_dp_train_step(
            model, tx, mesh, config, spec.input_kind)

    return mesh, model, source, state, train_step, sched, rng


def run(config: TrainConfig, *, total_steps: int,
        logger: Optional[MetricLogger] = None,
        warmup_steps: int = 0) -> dict[str, Any]:
    """Train for ``total_steps``; returns a summary with throughput.

    ``warmup_steps`` are excluded from timing (compile + first-step cost),
    matching the reference benchmark harness semantics (SURVEY.md §3.4).
    """
    logger = logger or MetricLogger()
    mesh, model, source, state, train_step, sched, rng = build(
        config, total_steps)
    if jax.process_index() == 0:
        # stderr so harness consumers (bench.py) keep a clean stdout
        print(f"# mesh: {meshlib.local_mesh_description(mesh)} | "
              f"model={config.model} global_batch={config.global_batch_size} "
              f"dtype={config.dtype}", file=sys.stderr, flush=True)

    metrics = {}
    timed_examples = 0
    # warmup_steps == 0 means "time everything" (incl. compile).
    t_timed = time.perf_counter() if warmup_steps == 0 else None
    for i in range(total_steps):
        state, metrics = train_step(state, source.batch(i), rng)
        if i + 1 == warmup_steps:
            jax.block_until_ready(metrics)
            t_timed = time.perf_counter()
        if (i + 1) % config.log_every == 0 or i + 1 == total_steps:
            jax.block_until_ready(metrics)
            logger.log(int(i + 1), metrics,
                       examples_per_step=config.global_batch_size,
                       lr=float(sched(i)))
        if i >= warmup_steps:
            timed_examples += config.global_batch_size

    jax.block_until_ready(state)
    summary: dict[str, Any] = {
        "final_step": total_steps,
        "final_metrics": {k: float(v) for k, v in metrics.items()},
    }
    if t_timed is not None and timed_examples:
        elapsed = time.perf_counter() - t_timed
        summary["examples_per_sec"] = timed_examples / elapsed
        summary["examples_per_sec_per_chip"] = (
            summary["examples_per_sec"] / jax.device_count())
        summary["steps_per_sec"] = (total_steps - warmup_steps) / elapsed
    return summary
