"""Train state pytree: params + optimizer state + BN statistics + step.

A plain ``flax.struct`` pytree (not TrainState from flax.training) so the
whole state threads through ``jit``/``shard_map`` and orbax untouched.

The state carries no layout assumptions: under ZeRO-1 optimizer sharding
(parallel/zero.py) ``opt_state``'s parameter-mirroring leaves are the
chunked global form — each a padded 1-D array of length ``chunk * N``
sharded 1/N over the DP axes — while everything else stays replicated.
:func:`resident_bytes` measures what a tree actually occupies on one
device under either layout.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp


def resident_bytes(tree: Any, device) -> int:
    """Bytes the leaves of ``tree`` occupy on ``device``, counting only the
    shards resident there — a fully replicated leaf contributes its full
    size, a 1/N-sharded leaf contributes 1/N. This is the per-device memory
    number the ZeRO-1 A/B (bench.py, run summaries) compares, and it works
    on every backend including CPU fake devices where allocator peak stats
    are unavailable."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += int(getattr(leaf, "nbytes", 0))
            continue
        for sh in shards:
            if sh.device == device:
                total += int(sh.data.nbytes)
    return total


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # scalar int32
    params: Any                       # model parameters (f32)
    opt_state: Any                    # optax state
    batch_stats: Any = None           # BN running stats (CNNs) or None
    ema_params: Any = None            # EMA shadow params (optimizer.ema_decay
                                      # > 0); evals read these when present
    loss_scale: Any = None            # dynamic loss-scale state
                                      # ({"scale", "good_steps"}) when the
                                      # precision policy arms scaling, else
                                      # None — None keeps the pytree identical
                                      # to pre-policy checkpoints

    @classmethod
    def create(cls, *, params: Any, opt_state: Any,
               batch_stats: Optional[Any] = None,
               ema_params: Optional[Any] = None,
               loss_scale: Optional[Any] = None) -> "TrainState":
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=opt_state, batch_stats=batch_stats,
                   ema_params=ema_params, loss_scale=loss_scale)
