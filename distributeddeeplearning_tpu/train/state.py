"""Train state pytree: params + optimizer state + BN statistics + step.

A plain ``flax.struct`` pytree (not TrainState from flax.training) so the
whole state threads through ``jit``/``shard_map`` and orbax untouched.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax.numpy as jnp


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # scalar int32
    params: Any                       # model parameters (f32)
    opt_state: Any                    # optax state
    batch_stats: Any = None           # BN running stats (CNNs) or None
    ema_params: Any = None            # EMA shadow params (optimizer.ema_decay
                                      # > 0); evals read these when present

    @classmethod
    def create(cls, *, params: Any, opt_state: Any,
               batch_stats: Optional[Any] = None,
               ema_params: Optional[Any] = None) -> "TrainState":
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=opt_state, batch_stats=batch_stats,
                   ema_params=ema_params)
