"""Trainers: one model-agnostic train loop serving every acceptance config
(the reference had one trainer per framework directory — SURVEY.md §2 #1-#3;
ours is one trainer, many models)."""

from distributeddeeplearning_tpu.train.state import TrainState  # noqa: F401
from distributeddeeplearning_tpu.train.optim import make_optimizer  # noqa: F401
