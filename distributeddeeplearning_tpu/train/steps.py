"""Compiled train/eval steps — the heart of the port.

Two parallel execution styles, both single XLA programs per step
(BASELINE.json:5: "replace hvd.DistributedOptimizer / hvd.allreduce with
jax.pmap/pjit emitting XLA psum over ICI"):

1. ``make_dp_train_step`` — ``shard_map`` over the (data, fsdp) mesh axes
   with replicated parameters and an explicit bucketed all-reduce on
   gradients (parallel/collectives.py). This is the literal
   Horovod-semantics path for the CNN configs: local BatchNorm (per-shard
   statistics, like per-GPU BN under Horovod), gradient averaging across
   shards, identical parameter update everywhere. Horovod's backward-hook +
   background-thread + fusion-buffer machinery maps onto the bucket planner:
   leaves fuse into size-targeted buckets, one collective each, which XLA
   overlaps with the remaining backward compute (SURVEY.md §3.1).

2. ``make_gspmd_train_step`` — ``jit`` + ``NamedSharding`` with logical-axis
   rules (parallel/sharding.py). Used for transformer workloads where
   parameters themselves shard (tp/fsdp) and activations shard over batch
   and sequence (dp/sp); XLA inserts every collective.

Both donate the input state (in-place update in HBM, no copy).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.config import TrainConfig, resolve_precision
from distributeddeeplearning_tpu.parallel import collectives
from distributeddeeplearning_tpu.parallel import sharding as shardlib
from distributeddeeplearning_tpu.parallel import zero
from distributeddeeplearning_tpu.parallel.mesh import use_mesh
from distributeddeeplearning_tpu.observability import telemetry
from distributeddeeplearning_tpu.robustness import faults
from distributeddeeplearning_tpu.train import losses
from distributeddeeplearning_tpu.train.state import TrainState

DATA_AXES = ("data", "fsdp")

# Trace-time counters, keyed by step name. A step function's Python body
# runs only while jax is TRACING it, so each counter increments exactly once
# per (re)trace — the probe tests use to assert that a warm restart loads
# its executable from the AOT cache without tracing at all.
TRACE_COUNTS: collections.Counter = collections.Counter()


def _aot_acquire(aot, name: str, jitted, args):
    """Resolve an ahead-of-time executable for ``jitted`` at ``args``' avals.

    Fingerprint hit: deserialize the saved executable (telemetry span
    ``aot_load``) — zero tracing. Miss: ``lower().compile()`` cold
    (telemetry span ``compile``) and serialize for the next attempt. The
    lowered ``Compiled`` object must be called directly — invoking the jit
    wrapper afterwards would re-trace, since AOT compilation bypasses jit's
    internal cache.
    """
    tele = telemetry.get()
    key = aot.key(name, args)
    t0 = time.perf_counter()
    fn = aot.load(name, key)
    if fn is not None:
        tele.record_span("aot_load", t0, time.perf_counter())
        return fn
    t0 = time.perf_counter()
    compiled_exec = jitted.lower(*args).compile()
    tele.record_span("compile", t0, time.perf_counter())
    aot.save(name, key, compiled_exec)
    return compiled_exec


def _inject_nan_grads(grads, step, nan_steps):
    """Fault injection (robustness/faults.py): poison the gradients of the
    updates whose pre-update ``state.step`` is in ``nan_steps``. Compiled in
    ONLY when a fault plan asks for it — the plan-free hot path carries no
    injection ops."""
    hit = jnp.zeros((), jnp.bool_)
    for s in nan_steps:
        hit = jnp.logical_or(hit, step == jnp.int32(s))
    return jax.tree_util.tree_map(
        lambda g: jnp.where(hit, jnp.full_like(g, jnp.nan), g), grads)


def _tree_sq_norm(tree):
    """Squared norm of a tree in f32 (finite iff every leaf is; values big
    enough to overflow the f32 sum also flag — such a step is equally
    unusable)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)


def _skip_if_bad(bad, new_tree, old_tree):
    """Bad-step guard: keep the pre-update value on every leaf when ``bad``.
    The select passes the already-computed new values through unchanged on
    good steps, so good-step numerics are value-identical."""
    if new_tree is None:
        return None
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(bad, o, n), new_tree, old_tree)


def _guard_config(config: TrainConfig):
    """(nan_steps, guard_on) for this build. The guard is compiled in only
    when asked for — explicitly (``bad_step_guard``) or implicitly by a plan
    that injects NaN gradients. It cannot be unconditionally on: keeping the
    pre-update state alive for the skip-select blocks the donated buffers'
    in-place reuse, which re-fuses the surrounding XLA program and drifts
    the trajectory ~1 ULP — breaking the zero1<->replicated bitwise pin
    (tests/test_zero1.py). Guard-free builds compile the exact seed program."""
    nan_steps = faults.resolve(config).nan_grad_steps()
    guard = bool(nan_steps) or bool(getattr(config, "bad_step_guard", False))
    return nan_steps, guard


def init_loss_scale(config: TrainConfig):
    """Initial dynamic-loss-scale state for ``TrainState.loss_scale``:
    ``{"scale", "good_steps"}`` device scalars when the precision policy
    arms scaling, None otherwise (the None keeps the state pytree — and
    therefore every existing checkpoint and sharding-spec derivation —
    byte-identical for policy-free configs)."""
    policy = resolve_precision(config)
    if policy.loss_scale <= 0:
        return None
    return {"scale": jnp.float32(policy.loss_scale),
            "good_steps": jnp.zeros((), jnp.int32)}


def _next_loss_scale(policy, scale, good_steps, overflow):
    """The dynamic-scale automaton, shared by both train-step paths:
    overflow -> halve (floored at loss_scale_min), ``growth_interval``
    consecutive good steps -> double (capped at loss_scale_max). Returns
    (new_state_dict, metrics_dict); the caller applies the update skip."""
    good = good_steps + jnp.int32(1)
    grow = good >= jnp.int32(policy.loss_scale_growth_interval)
    new_scale = jnp.where(
        overflow,
        jnp.maximum(scale * jnp.float32(0.5),
                    jnp.float32(policy.loss_scale_min)),
        jnp.where(grow,
                  jnp.minimum(scale * jnp.float32(2.0),
                              jnp.float32(policy.loss_scale_max)),
                  scale))
    new_good = jnp.where(jnp.logical_or(overflow, grow), jnp.int32(0), good)
    # ``loss_scale_skip`` is deliberately NOT ``bad_step``: a backoff is
    # the scaler doing its job, and the bad-step anomaly tracker
    # (train/loop.py) must never count one as a run anomaly.
    return ({"scale": new_scale, "good_steps": new_good},
            {"loss_scale": new_scale,
             "loss_scale_skip": overflow.astype(jnp.float32)})


def _ema_update(ema, new_params, decay: float):
    """Shadow-param EMA: e <- d*e + (1-d)*p. None stays None (off)."""
    if ema is None:
        return None
    d = jnp.float32(decay)
    return jax.tree_util.tree_map(
        lambda e, p: (d * e + (1.0 - d) * p).astype(p.dtype),
        ema, new_params)


# ---------------------------------------------------------------------------
# Forward/loss closures per input kind
# ---------------------------------------------------------------------------

def _image_loss_fn(model, config: TrainConfig):
    smoothing = config.optimizer.label_smoothing

    def loss_fn(params, batch_stats, batch, rng):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        # rngs is harmless for dropout-free CNNs and required for image
        # transformers (models/vit.py); per-shard/per-step folding happens in
        # the calling step fn.
        out, mutated = model.apply(
            variables, batch["image"], train=True, mutable=["batch_stats"],
            rngs={"dropout": rng})
        loss = losses.smoothed_softmax_ce(out, batch["label"], smoothing)
        metrics = {"loss": loss,
                   "accuracy": losses.top1_accuracy(out, batch["label"])}
        return loss, (mutated.get("batch_stats"), metrics)

    return loss_fn


def _token_loss_fn(model, config: TrainConfig):
    del config
    # MoE models sow per-layer load-balance losses into "moe_losses"
    # (models/moe.py); weight comes from the model's own config so dense
    # models pay nothing.
    aux_weight = getattr(getattr(model, "cfg", None), "moe_aux_weight", 0.0)

    def loss_fn(params, batch_stats, batch, rng):
        del batch_stats
        kw = {}
        if "masked_positions" in batch:  # gather-mode head (BertMLM)
            kw["masked_positions"] = batch["masked_positions"]
        logits, mutated = model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            train=True, rngs={"dropout": rng}, mutable=["moe_losses"], **kw)
        loss = losses.mlm_loss(
            logits, batch.get("masked_labels", batch.get("labels")))
        metrics = {"loss": loss}
        aux_leaves = jax.tree_util.tree_leaves(mutated.get("moe_losses", {}))
        if aux_leaves:
            aux = sum(aux_leaves) / len(aux_leaves)
            loss = loss + aux_weight * aux
            metrics["moe_aux"] = aux
        return loss, (None, metrics)

    return loss_fn


def _causal_loss_fn(model, config: TrainConfig):
    del config

    def loss_fn(params, batch_stats, batch, rng):
        del batch_stats
        logits = model.apply(
            {"params": params}, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            train=True, rngs={"dropout": rng})
        loss = losses.causal_lm_loss(
            logits, batch["input_ids"], batch.get("attention_mask"))
        return loss, (None, {"loss": loss})

    return loss_fn


def loss_fn_for(model, input_kind: str, config: TrainConfig,
                objective: str = "classify"):
    if input_kind == "image":
        return _image_loss_fn(model, config)
    if input_kind == "tokens":
        if objective == "causal":
            return _causal_loss_fn(model, config)
        return _token_loss_fn(model, config)
    raise ValueError(f"unknown input kind {input_kind!r}")


# ---------------------------------------------------------------------------
# Gradient accumulation (config 5: batch=32k on any mesh — VERDICT r1 #3)
# ---------------------------------------------------------------------------

def accumulated_grads(loss_fn, params, batch_stats, batch, rng, accum: int,
                      vary_axes=None):
    """Gradients for ``batch``, optionally microbatched via ``lax.scan``.

    With ``accum > 1`` the leading batch dim splits into ``accum`` equal
    microbatches; per-microbatch gradients are summed in a scan carry and
    divided once at the end — mathematically the big-batch *mean* gradient
    (exact for any loss that is a mean over examples, hence for SGD/LARS
    updates up to fp summation order). Activation memory drops by ~accum
    while the optimizer still sees one batch=32k update, which is what lets
    the LARS recipe execute on an 8-chip (or 8-fake-CPU) mesh.

    BatchNorm statistics are updated sequentially through the scan (each
    microbatch normalizes with its own statistics, exactly like running the
    microbatches as separate steps); metrics are averaged over microbatches.
    Returns ``(grads, new_batch_stats, metrics)``.
    """
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if accum <= 1:
        (_, (new_bn, metrics)), grads = grad_fn(params, batch_stats, batch, rng)
        return grads, new_bn, metrics

    micro = jax.tree_util.tree_map(
        lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]), batch)
    if vary_axes is not None and batch_stats is not None:
        # Under shard_map's varying-manual-axes check the replicated input
        # stats are unvarying while updated stats (computed from the sharded
        # batch) vary over the DP axes — the scan carry must enter varying.
        # (compat.shard_map runs with the check off, where this is identity.)
        batch_stats = compat.pvary(batch_stats, vary_axes)

    def body(carry, xs):
        grads_acc, bn = carry
        mb, idx = xs
        (_, (new_bn, metrics)), grads = grad_fn(
            params, bn, mb, jax.random.fold_in(rng, idx))
        grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
        if new_bn is None:
            new_bn = bn
        return (grads_acc, new_bn), metrics

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (grads_sum, new_bn), metrics = jax.lax.scan(
        body, (zeros, batch_stats), (micro, jnp.arange(accum)))
    grads = jax.tree_util.tree_map(lambda g: g / accum, grads_sum)
    metrics = jax.tree_util.tree_map(jnp.mean, metrics)
    return grads, new_bn, metrics


# ---------------------------------------------------------------------------
# Path 1: explicit-collective DP (shard_map + psum) — Horovod semantics
# ---------------------------------------------------------------------------

def make_dp_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                       config: TrainConfig, input_kind: str = "image",
                       objective: str = "classify",
                       state_like: Optional[TrainState] = None,
                       aot=None, zero_layout=None, params_struct=None
                       ) -> Callable[[TrainState, Any, jax.Array],
                                     tuple[TrainState, dict]]:
    """Build the jitted data-parallel train step.

    state: fully replicated. batch: leading dim sharded over (data, fsdp).
    Per-shard gradients are summed across the DP axes by the bucketed fused
    all-reduce (``config.allreduce``: bucket size / payload dtype /
    psum-vs-ring) and divided by the shard count — the exact
    allreduce-average Horovod performs — so parameters stay bit-identical
    on every shard. BN running-stat updates are ``pmean``-ed likewise.

    ``config.optimizer_sharding`` climbs the ZeRO ladder (parallel/zero.py):

    - ``zero1`` — the gradient sync stops at the ring's halfway point: one
      ``psum_scatter`` per fusion bucket leaves each shard the reduced 1/N
      chunk of every leaf, the optax update runs on that chunk against
      permanently 1/N-sharded optimizer state, and the trailing
      ``all_gather`` moves the *updated parameters* — same wire bytes as
      the ring all-reduce, optimizer HBM/compute divided by N.
    - ``zero2`` — same update math, but the loss is differentiated w.r.t.
      the parameter CHUNKS through a per-bucket identity ``custom_vjp``
      whose backward rule IS the bucket reduce-scatter: gradients are born
      reduce-scattered during backward (overlapping remaining backward
      compute) and the full gradient tree never materializes.
    - ``zero3`` — parameters themselves live in the chunked global form
      (``state.params`` leaves are padded flat ``(chunk*N,)`` arrays
      sharded over the DP axes) and are all-gathered on demand per bucket
      in forward, the gather's backward rule again the bucket
      reduce-scatter. No parameter all-gather after the update — the
      chunks ARE the persistent state.

    ``config.overlap_collectives=False`` downgrades zero2/zero3 to the
    serialized schedule (full grads after backward, one scatter pass) for
    A/B measurement; update arithmetic is unchanged.

    For any sharded stage ``state_like`` (the initialized TrainState) is
    required — it supplies per-leaf partition specs for shard_map. Under
    zero3 its params are chunked, so the FULL-shape ``params_struct`` and
    the ``zero_layout`` built from it must be passed in (train/loop.py
    does); other stages can derive both from ``state_like.params``.

    ``aot`` (a perf.aot.StepExecutableCache) switches the first call to the
    ahead-of-time path: load the serialized executable for this config
    fingerprint, or ``lower().compile()`` once and serialize it so the next
    launch / restart attempt skips tracing entirely (docs/compile_cache.md).
    """
    loss_fn = loss_fn_for(model, input_kind, config, objective)
    dp_size = mesh.shape["data"] * mesh.shape["fsdp"]
    accum = config.grad_accum_steps

    # Precision policy (config.resolve_precision). With no explicit policy
    # every derived value below collapses to the legacy behavior —
    # ar_options IS config.allreduce, no loss scaling, fp32 gathers — so
    # policy-free configs compile the exact seed program (and keep the
    # zero1<->replicated bitwise pin). An explicit policy re-points the
    # reduction payload at policy.reduce_dtype and, for bf16 compute,
    # gathers zero3 params on the wire in bf16 while the persistent chunks
    # (the masters the optimizer updates) stay fp32.
    policy = resolve_precision(config)
    scaling = config.precision is not None and policy.loss_scale > 0
    ar_options = (dataclasses.replace(config.allreduce,
                                      dtype=policy.reduce_dtype)
                  if config.precision is not None else config.allreduce)
    gather_dtype = (jnp.bfloat16
                    if (config.precision is not None
                        and policy.compute_dtype == "bfloat16")
                    else None)

    nan_steps, guard = _guard_config(config)
    stage = getattr(config, "optimizer_sharding", "none") or "none"
    sharded = stage in ("zero1", "zero2", "zero3")
    overlap = (stage in ("zero2", "zero3")
               and getattr(config, "overlap_collectives", True))
    layout = payload = None
    if sharded:
        if state_like is None:
            raise ValueError(
                f"optimizer_sharding={stage!r} requires state_like= (the "
                "initialized TrainState) so the step can derive the chunk "
                "layout and per-leaf optimizer-state partition specs")
        if params_struct is None:
            if stage == "zero3":
                raise ValueError(
                    "optimizer_sharding='zero3' requires params_struct= "
                    "(full parameter shapes) — state_like.params is already "
                    "chunked and cannot seed the layout")
            params_struct = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype),
                state_like.params)
        if zero_layout is not None:
            layout = zero_layout
            payload = zero.payload_dtype_from_options(ar_options)
        else:
            layout, payload = zero.layout_from_options(
                params_struct, dp_size, options=ar_options)

    def step_fn(state: TrainState, batch, rng):
        TRACE_COUNTS["dp_train_step"] += 1  # trace-time only, not per call
        # Per-shard RNG: fold in the linearized DP coordinate.
        idx = jax.lax.axis_index(DATA_AXES)
        rng = jax.random.fold_in(jax.random.fold_in(rng, idx), state.step)

        # Dynamic loss scaling: scale the differentiated scalar only — the
        # aux metrics (including metrics["loss"]) stay unscaled, and the
        # gradients come out uniformly multiplied by the scale, which the
        # unscale below divides back out after the cross-shard reduction.
        if scaling:
            ls_scale = state.loss_scale["scale"]

            def lfn(p, bn, b, r):
                loss, aux = loss_fn(p, bn, b, r)
                return loss * ls_scale, aux
        else:
            lfn = loss_fn

        # Per-shard microbatching: the reshape is shard-local (free), and the
        # sum-over-examples gradient is grouping-invariant, so accum-N here
        # equals the one-shot big-batch gradient. (With the overlapped
        # zero2/zero3 schedules each microbatch issues its own per-bucket
        # scatters, so the cross-shard sum order differs from zero1's single
        # post-accumulation scatter — same math, not bitwise; accum=1 is.)
        gchunks = pchunks = None
        if stage == "zero3":
            # Inside shard_map the P(DATA_AXES) in_spec on the chunked
            # global form means state.params leaves ARE this shard's local
            # (chunk,) slices — no dynamic_slice needed.
            pchunks = state.params
            if overlap:
                def chunk_loss(pc, bn, b, r):
                    full = zero.gather_params_overlapped(
                        pc, layout, DATA_AXES, payload_dtype=payload,
                        out_dtype=gather_dtype)
                    return lfn(full, bn, b, r)
                gchunks, new_bn, metrics = accumulated_grads(
                    chunk_loss, pchunks, state.batch_stats, batch, rng,
                    accum, vary_axes=DATA_AXES)
            else:
                full = zero.all_gather_chunks(pchunks, layout, DATA_AXES,
                                              out_dtype=gather_dtype)
                grads, new_bn, metrics = accumulated_grads(
                    lfn, full, state.batch_stats, batch, rng, accum,
                    vary_axes=DATA_AXES)
        elif stage == "zero2" and overlap:
            pchunks = zero.local_chunks(state.params, layout, DATA_AXES)

            def chunk_loss(pc, bn, b, r):
                # state.params enters as a closure CONSTANT (the identity
                # forward), so only the chunk cotangents survive — the full
                # gradient tree is never a live value.
                full = zero.assemble_params_overlapped(
                    state.params, pc, layout, DATA_AXES,
                    payload_dtype=payload)
                return lfn(full, bn, b, r)

            gchunks, new_bn, metrics = accumulated_grads(
                chunk_loss, pchunks, state.batch_stats, batch, rng, accum,
                vary_axes=DATA_AXES)
        else:
            grads, new_bn, metrics = accumulated_grads(
                lfn, state.params, state.batch_stats, batch, rng, accum,
                vary_axes=DATA_AXES)

        if nan_steps:
            if gchunks is not None:
                gchunks = _inject_nan_grads(gchunks, state.step, nan_steps)
            else:
                grads = _inject_nan_grads(grads, state.step, nan_steps)

        metrics = jax.lax.pmean(metrics, DATA_AXES)
        if new_bn is not None:
            # Sync running statistics (cheap; normalization itself stayed
            # local per shard, matching per-GPU BN under Horovod).
            new_bn = jax.lax.pmean(new_bn, DATA_AXES)

        if sharded:
            # Shard-local optimizer update on this shard's 1/N chunk of
            # every leaf. `tx` was built with shard_axes=DATA_AXES
            # (train/optim.py), so any cross-leaf norms (global clip,
            # LARS/LAMB trust ratios) psum their squared sums and the
            # chunked update matches the replicated one per element.
            if gchunks is None:
                # zero1 / overlap-off schedules: full gradient tree was
                # materialized; run the ring's first half now.
                gchunks = zero.reduce_scatter(grads, layout, DATA_AXES,
                                              payload_dtype=payload)
            gchunks = jax.tree_util.tree_map(lambda g: g / dp_size, gchunks)
            if scaling:
                # Overflow check on the still-scaled chunks, then unscale.
                # Each shard holds 1/N of every leaf, so the squared norm
                # needs one psum to make the verdict shard-consistent.
                overflow = ~jnp.isfinite(
                    jax.lax.psum(_tree_sq_norm(gchunks), DATA_AXES))
                gchunks = jax.tree_util.tree_map(
                    lambda g: g / ls_scale, gchunks)
            if pchunks is None:
                pchunks = zero.local_chunks(state.params, layout, DATA_AXES)
            updates, new_opt = tx.update(gchunks, state.opt_state, pchunks)
            new_pchunks = optax.apply_updates(pchunks, updates)
            if stage == "zero3":
                # Chunks ARE the persistent parameter layout — no gather.
                new_params = new_pchunks
            else:
                new_params = zero.all_gather_chunks(new_pchunks, layout,
                                                    DATA_AXES)
        else:
            # The allreduce. compat.shard_map runs with replication checking
            # OFF, so autodiff does NOT auto-psum gradients for the
            # replicated params — `grads` arrives here shard-LOCAL, and this
            # train step owns the reduction schedule: leaves fuse into
            # size-targeted buckets, one collective per bucket (Horovod
            # tensor fusion), with each bucket an independent dataflow edge
            # XLA can overlap with remaining backward compute. Dividing the
            # sum by the shard count turns the ring-allreduce-sum into the
            # gradient *average* hvd applies.
            grads = collectives.all_reduce_gradients(
                grads, DATA_AXES, axis_size=dp_size,
                options=ar_options)
            grads = jax.tree_util.tree_map(lambda g: g / dp_size, grads)
            if scaling:
                # Post-all-reduce gradients are shard-identical, so the
                # overflow verdict is shard-consistent without a collective.
                overflow = ~jnp.isfinite(_tree_sq_norm(grads))
                grads = jax.tree_util.tree_map(lambda g: g / ls_scale, grads)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)

        new_ema = _ema_update(state.ema_params, new_params,
                              config.optimizer.ema_decay)
        new_ls = state.loss_scale
        if scaling:
            # Loss-scale skip-on-overflow: same select machinery as the
            # bad-step guard but applied FIRST and accounted separately
            # (``loss_scale_skip``, never ``bad_step``) — a scale backoff is
            # normal mixed-precision operation, not a run anomaly, and the
            # guard below must see the already-restored (finite) state so a
            # backoff can never double-count.
            new_params = _skip_if_bad(overflow, new_params, state.params)
            new_opt = _skip_if_bad(overflow, new_opt, state.opt_state)
            new_bn = _skip_if_bad(overflow, new_bn, state.batch_stats)
            new_ema = _skip_if_bad(overflow, new_ema, state.ema_params)
            new_ls, ls_metrics = _next_loss_scale(
                policy, ls_scale, state.loss_scale["good_steps"], overflow)
            metrics.update(ls_metrics)
        if guard:
            # Bad-step guard (docs/fault_tolerance.md). The decision must be
            # identical on every shard, so derive it ONLY from values that
            # already are: the pmean'd loss and the post-update params
            # (post-all-reduce here, post-all-gather under zero1).
            # Non-finite grads on ANY shard propagate through the reduction
            # and the optimizer into the params, so checking the result
            # catches them — one local (collective-free) reduction per step,
            # except under zero3 where new_params is this shard's chunks
            # only and the norm needs a psum to stay shard-consistent.
            sq = _tree_sq_norm(new_params)
            if stage == "zero3":
                sq = jax.lax.psum(sq, DATA_AXES)
            bad = jnp.logical_or(~jnp.isfinite(metrics["loss"]),
                                 ~jnp.isfinite(sq))
            if scaling:
                # An overflow step already skipped above; even if its loss
                # was non-finite, the scaler owns it — not the anomaly
                # budget.
                bad = jnp.logical_and(bad, jnp.logical_not(overflow))
            # Skip-on-bad: the step index still advances (the batch is
            # consumed; a skip is a skip, not a retry), but params/opt/BN/
            # EMA keep their pre-update values so one poisoned batch can't
            # wreck the run.
            new_params = _skip_if_bad(bad, new_params, state.params)
            new_opt = _skip_if_bad(bad, new_opt, state.opt_state)
            new_bn = _skip_if_bad(bad, new_bn, state.batch_stats)
            new_ema = _skip_if_bad(bad, new_ema, state.ema_params)
            metrics["bad_step"] = bad.astype(jnp.float32)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt, batch_stats=new_bn,
                               ema_params=new_ema, loss_scale=new_ls)
        return new_state, metrics

    batch_spec = P(DATA_AXES)
    if sharded:
        # Everything replicated EXCEPT the chunked leaves, which shard dim 0
        # over the DP axes (each shard sees its chunk): the opt state at
        # every stage, plus params/ema at zero3.
        opt_spec = zero.opt_state_specs(tx, params_struct, layout,
                                        P(DATA_AXES), P())
        state_spec = jax.tree_util.tree_map(lambda _: P(), state_like)
        state_spec = state_spec.replace(opt_state=opt_spec)
        if stage == "zero3":
            state_spec = state_spec.replace(
                params=jax.tree_util.tree_map(lambda _: P(DATA_AXES),
                                              state_like.params))
            if state_like.ema_params is not None:
                state_spec = state_spec.replace(
                    ema_params=jax.tree_util.tree_map(
                        lambda _: P(DATA_AXES), state_like.ema_params))
    else:
        state_spec = P()
    mapped = compat.shard_map(
        step_fn, mesh=mesh,
        in_specs=(state_spec, batch_spec, P()),
        out_specs=(state_spec, P()))
    jitted = jax.jit(mapped, donate_argnums=0)
    aot_exec = {"fn": None, "resolved": aot is None or not aot.enabled}

    def compiled(state, batch, rng):
        if not aot_exec["resolved"]:
            # First call: bind the AOT executable at these argument avals.
            # Donation (argnums=0) is baked into the lowering, so the
            # Compiled object updates state in place exactly like the jit.
            aot_exec["resolved"] = True
            aot_exec["fn"] = _aot_acquire(aot, "dp_train_step", jitted,
                                          (state, batch, rng))
        if aot_exec["fn"] is not None:
            return aot_exec["fn"](state, batch, rng)
        return jitted(state, batch, rng)

    def warm(state_struct, batch, rng) -> bool:
        """Resolve the step executable from abstract avals without
        executing. The elastic restore/compile overlap (train/loop.py) runs
        this on a background thread while ``restore_latest`` deserializes
        the checkpoint, so a re-formed attempt pays max(restore, compile)
        instead of their sum. ``state_struct`` must carry the live state's
        shardings (ShapeDtypeStruct with sharding=) — same contract as the
        evaluator's warm_compile_async. Returns False (cold path intact) on
        any failure; warm-up is optional."""
        if aot_exec["fn"] is not None:
            return True
        try:
            if aot is not None and aot.enabled:
                fn = _aot_acquire(aot, "dp_train_step", jitted,
                                  (state_struct, batch, rng))
            else:
                t0 = time.perf_counter()
                fn = jitted.lower(state_struct, batch, rng).compile()
                telemetry.get().record_span("compile", t0,
                                            time.perf_counter())
            aot_exec["fn"] = fn
            aot_exec["resolved"] = True
            return True
        except Exception:  # noqa: BLE001 - warm-up is optional
            return False

    compiled.warm = warm
    # Raw traceable step for the fused multi-step loop
    # (make_fused_train_loop): shard_map composes under an outer jit+scan.
    compiled.raw_step = mapped
    compiled.zero_layout = layout
    compiled.zero_stage = stage if sharded else None
    compiled.overlap = overlap
    # Per-device gradient residency for the memory-ladder accounting
    # (train/loop.py, bench.py): gradients are transient, so this is a
    # model, not a measurement — see zero.modeled_grad_bytes.
    if layout is not None:
        compiled.grad_bytes_per_device = zero.modeled_grad_bytes(
            layout, chunked=overlap)
    elif state_like is not None:
        compiled.grad_bytes_per_device = zero.modeled_grad_bytes(
            zero.build_layout(state_like.params, 1), chunked=False)
    else:
        compiled.grad_bytes_per_device = None
    return compiled


def make_token_eval_step(model, mesh: Mesh, config: TrainConfig,
                         state_shardings, objective: str = "mlm"):
    """Held-out LM eval (GSPMD): per-batch (loss_sum, token_count) with
    dropout off — exact aggregation across any sharding, so perplexity is
    identical to a single-device pass (the token analogue of the image
    path's psum'd correct-counts, SURVEY.md §3.5)."""

    def eval_fn(state: TrainState, batch):
        TRACE_COUNTS["token_eval_step"] += 1
        kw = {}
        if objective != "causal" and "masked_positions" in batch:
            kw["masked_positions"] = batch["masked_positions"]
        with _unreplicated_rules_ctx(config):
            logits = model.apply(
                {"params": state.params}, batch["input_ids"],
                attention_mask=batch.get("attention_mask"), train=False, **kw)
        if objective == "causal":
            s, n = losses.causal_lm_loss_sums(
                logits, batch["input_ids"], batch.get("attention_mask"))
        else:
            s, n = losses.mlm_loss_sums(
                logits, batch.get("masked_labels", batch.get("labels")))
        return {"loss_sum": s, "count": n}

    jit_cache: dict = {}

    def compiled(state, batch):
        key = jax.tree_util.tree_structure(batch)
        if key not in jit_cache:
            jit_cache[key] = jax.jit(
                eval_fn,
                in_shardings=(state_shardings, None),
                out_shardings=NamedSharding(mesh, P()))
        with use_mesh(mesh):
            return jit_cache[key](state, batch)

    def lower_for(state, batch):
        """AOT entry for the eval warm-compile overlap (train/loop.py):
        lower at abstract avals without executing. The caller keeps the
        returned Lowered's ``compile()`` result and must call IT — jit's
        internal cache is not populated by AOT compilation."""
        key = jax.tree_util.tree_structure(batch)
        if key not in jit_cache:
            jit_cache[key] = jax.jit(
                eval_fn,
                in_shardings=(state_shardings, None),
                out_shardings=NamedSharding(mesh, P()))
        with use_mesh(mesh):
            return jit_cache[key].lower(state, batch)

    compiled.lower_for = lower_for
    return compiled


def make_dp_eval_step(model, mesh: Mesh, config: TrainConfig):
    """Eval: per-shard correct-count, psum before dividing (SURVEY.md §3.5)."""
    del config

    def eval_fn(state: TrainState, batch):
        TRACE_COUNTS["dp_eval_step"] += 1
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, batch["image"], train=False)
        correct = (jnp.argmax(logits, -1) == batch["label"]).sum()
        total = jnp.asarray(batch["label"].shape[0], jnp.int32)
        correct = jax.lax.psum(correct, DATA_AXES)
        total = jax.lax.psum(total, DATA_AXES)
        return {"correct": correct, "total": total}

    mapped = compat.shard_map(
        eval_fn, mesh=mesh, in_specs=(P(), P(DATA_AXES)),
        out_specs=P())
    return jax.jit(mapped)


# ---------------------------------------------------------------------------
# Path 2: GSPMD (jit + NamedSharding) — tp/sp/fsdp for transformers
# ---------------------------------------------------------------------------

def _unreplicated_rules_ctx(config: TrainConfig):
    return nn.logical_axis_rules(list(shardlib.logical_rules(config.parallel)))


def _batch_leaf_shardings(mesh: Mesh, batch_shd, batch):
    """Leading-dim batch sharding for array leaves, replicated for scalars —
    the one rule both the per-step GSPMD jit and the fused loop use."""
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: batch_shd if getattr(x, "ndim", 0) >= 1 else rep, batch)


def _zero2_opt_state_shardings(mesh: Mesh, abstract_opt, shardings_opt):
    """ZeRO-2 composition for the GSPMD *pipelined* path: re-spec each
    optimizer-state leaf to also shard over the DP axes on its first free
    (unsharded, divisible) dimension. Stage/tp dims keep their axes, so a
    moment chunk lives inside its stage's DP group — XLA then lowers the
    gradient reduction feeding the update into a reduce-scatter per group
    and all-gathers the applied updates, the per-bucket dataflow the
    explicit shard_map path builds by hand in parallel/zero.py
    (docs/pipeline.md "Composing with ZeRO-2"). Leaves with no divisible
    free dim (scalars, odd shapes) stay on their param spec — partial
    sharding, same rule as the explicit layout planner."""
    dp_axes = tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)
    if not dp_axes:
        return shardings_opt
    dp = 1
    for a in dp_axes:
        dp *= mesh.shape[a]

    def shard_leaf(aval, shd):
        shape = getattr(aval, "shape", ())
        if not shape or not isinstance(shd, NamedSharding):
            return shd
        spec = list(shd.spec) + [None] * (len(shape) - len(shd.spec))
        for d, size in enumerate(shape):
            if spec[d] is None and size and size % dp == 0:
                spec[d] = dp_axes if len(dp_axes) > 1 else dp_axes[0]
                return NamedSharding(mesh, P(*spec))
        return shd

    return jax.tree_util.tree_map(
        shard_leaf, nn.meta.unbox(abstract_opt), shardings_opt)


def init_sharded_state(model, tx, mesh: Mesh, config: TrainConfig,
                       example_batch: Any, rng: jax.Array,
                       input_kind: str = "tokens", aot=None):
    """Initialize a TrainState whose params/opt-state are laid out per the
    logical sharding rules, created directly on-device via jit out_shardings
    (no host-side full materialization).

    With an ``aot`` cache the init program itself is fingerprint-keyed like
    the train step: a re-formed elastic attempt (or any warm boot of an
    identical config) deserializes it instead of re-compiling — the init
    values are overwritten by the checkpoint restore anyway, so the compile
    it skips was pure outage time (reconfiguration ``spawn_s``)."""

    def init_fn(rng):
        with _unreplicated_rules_ctx(config):
            if input_kind == "tokens":
                variables = model.init(
                    {"params": rng, "dropout": rng},
                    example_batch["input_ids"], train=False)
            else:
                variables = model.init(
                    {"params": rng}, example_batch["image"], train=False)
        params = variables["params"]
        opt_state = tx.init(params)
        return TrainState.create(
            params=params, opt_state=opt_state,
            batch_stats=variables.get("batch_stats"),
            ema_params=(params if config.optimizer.ema_decay > 0
                        else None),
            loss_scale=init_loss_scale(config))

    with use_mesh(mesh):  # model may embed mesh-dependent shard_maps (ring)
        abstract = jax.eval_shape(init_fn, rng)
    with _unreplicated_rules_ctx(config):
        specs = nn.logical_to_mesh(nn.get_partition_spec(abstract))
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P))
    if (config.optimizer_sharding == "zero2"
            and getattr(getattr(model, "cfg", None), "pipeline_stages", 1)
            > 1):
        shardings = shardings.replace(opt_state=_zero2_opt_state_shardings(
            mesh, abstract.opt_state, shardings.opt_state))
    with use_mesh(mesh):
        jitted = jax.jit(init_fn, out_shardings=shardings)
        if aot is not None and aot.enabled:
            jitted = _aot_acquire(aot, "gspmd_init", jitted, (rng,))
        state = jitted(rng)
    return state, shardings


def make_gspmd_train_step(model, tx, mesh: Mesh, config: TrainConfig,
                          state_shardings, input_kind: str = "tokens",
                          objective: str = "mlm", aot=None):
    loss_fn = loss_fn_for(model, input_kind, config, objective)
    nan_steps, bad_guard = _guard_config(config)
    policy = resolve_precision(config)
    scaling = config.precision is not None and policy.loss_scale > 0
    # Token batches are (B, S): dim 0 over the DP axes, dim 1 over `seq`.
    seq_dim = 1 if input_kind == "tokens" else None
    batch_shd = shardlib.batch_sharding(mesh, seq_dim=seq_dim)

    def step_fn(state: TrainState, batch, rng):
        TRACE_COUNTS["gspmd_train_step"] += 1
        rng = jax.random.fold_in(rng, state.step)
        if scaling:
            ls_scale = state.loss_scale["scale"]

            def lfn(p, bn, b, r):
                loss, aux = loss_fn(p, bn, b, r)
                return loss * ls_scale, aux
        else:
            lfn = loss_fn
        with _unreplicated_rules_ctx(config):
            # Microbatching under GSPMD: the (B,) -> (A, B/A) reshape crosses
            # the dp sharding, so XLA may insert a small resharding collective
            # on the *batch* (token batches are tiny; image configs use the
            # shard-local DP path above instead). Caveat: SPMD propagation
            # has been observed (jax 0.4.37) to realize this contiguous
            # split as the shard-local grouping — for a loss that is a plain
            # per-example mean the accumulated gradient is grouping-
            # invariant, but it is NOT guaranteed mesh-stable for
            # group-normalized losses; the pipeline conveyor hit the same
            # pattern and moved to a strided split (models/pipeline.py).
            grads, new_bn, metrics = accumulated_grads(
                lfn, state.params, state.batch_stats, batch, rng,
                config.grad_accum_steps)
        if nan_steps:
            grads = _inject_nan_grads(grads, state.step, nan_steps)
        if scaling:
            # One logical program: XLA inserts whatever cross-shard
            # reduction the norm needs, so the verdict is globally
            # consistent without an explicit psum.
            overflow = ~jnp.isfinite(_tree_sq_norm(grads))
            grads = jax.tree_util.tree_map(lambda g: g / ls_scale, grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_ema = _ema_update(state.ema_params, new_params,
                              config.optimizer.ema_decay)
        new_ls = state.loss_scale
        if scaling:
            new_params = _skip_if_bad(overflow, new_params, state.params)
            new_opt = _skip_if_bad(overflow, new_opt, state.opt_state)
            new_bn = _skip_if_bad(overflow, new_bn, state.batch_stats)
            new_ema = _skip_if_bad(overflow, new_ema, state.ema_params)
            new_ls, ls_metrics = _next_loss_scale(
                policy, ls_scale, state.loss_scale["good_steps"], overflow)
            metrics.update(ls_metrics)
        if bad_guard:
            # Bad-step guard on the post-update params (same placement as
            # the DP path). One logical program: XLA inserts any cross-shard
            # reduction the norm needs, so the scalar is globally
            # consistent without an explicit psum.
            bad = jnp.logical_or(~jnp.isfinite(metrics["loss"]),
                                 ~jnp.isfinite(_tree_sq_norm(new_params)))
            if scaling:
                bad = jnp.logical_and(bad, jnp.logical_not(overflow))
            new_params = _skip_if_bad(bad, new_params, state.params)
            new_opt = _skip_if_bad(bad, new_opt, state.opt_state)
            new_bn = _skip_if_bad(bad, new_bn, state.batch_stats)
            new_ema = _skip_if_bad(bad, new_ema, state.ema_params)
            metrics["bad_step"] = bad.astype(jnp.float32)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt, batch_stats=new_bn,
                               ema_params=new_ema, loss_scale=new_ls)
        return new_state, metrics

    batch_shardings = functools.partial(_batch_leaf_shardings, mesh, batch_shd)

    jit_cache: dict = {}

    def compiled(state, batch, rng):
        # One jit wrapper per batch structure — recreating the wrapper per
        # call would discard the compilation cache. With an AOT cache the
        # wrapper resolves once to an executable (same contract as the dp
        # path): fingerprint hit deserializes it — zero retraces, so a
        # pipelined warm boot skips the whole schedule trace — and a miss
        # lower().compile()s and saves it for the next attempt.
        key = jax.tree_util.tree_structure(batch)
        if key not in jit_cache:
            jitted = jax.jit(
                step_fn,
                in_shardings=(state_shardings, batch_shardings(batch),
                              NamedSharding(mesh, P())),
                out_shardings=(state_shardings, NamedSharding(mesh, P())),
                donate_argnums=0)
            if aot is not None and aot.enabled:
                with use_mesh(mesh):
                    jitted = _aot_acquire(aot, "gspmd_train_step", jitted,
                                          (state, batch, rng))
            jit_cache[key] = jitted
        with use_mesh(mesh):
            return jit_cache[key](state, batch, rng)

    def warm(state_struct, batch, rng) -> bool:
        """GSPMD twin of the DP path's ``warm``: populate the per-structure
        cache from abstract avals (elastic restore/compile overlap). The
        explicit in_shardings make struct lowering exact — the executable
        the first real call would have built."""
        key = jax.tree_util.tree_structure(batch)
        if key in jit_cache:
            return True
        try:
            jitted = jax.jit(
                step_fn,
                in_shardings=(state_shardings, batch_shardings(batch),
                              NamedSharding(mesh, P())),
                out_shardings=(state_shardings, NamedSharding(mesh, P())),
                donate_argnums=0)
            with use_mesh(mesh):
                if aot is not None and aot.enabled:
                    jitted = _aot_acquire(aot, "gspmd_train_step", jitted,
                                          (state_struct, batch, rng))
                else:
                    t0 = time.perf_counter()
                    jitted = jitted.lower(state_struct, batch, rng).compile()
                    telemetry.get().record_span("compile", t0,
                                                time.perf_counter())
            jit_cache[key] = jitted
            return True
        except Exception:  # noqa: BLE001 - warm-up is optional
            return False

    compiled.warm = warm
    compiled.raw_step = step_fn
    compiled.state_shardings = state_shardings
    return compiled


# ---------------------------------------------------------------------------
# Fused multi-step loop (steps_per_loop) — dispatch-latency amortization
# ---------------------------------------------------------------------------

def make_fused_train_loop(train_step, source, batch_shd, mesh: Mesh):
    """Fuse K train steps + on-device batch generation into ONE XLA program.

    The TPU analogue of TF/TPUEstimator's ``iterations_per_loop``: when the
    batch is a pure on-device function of ``(seed, step)`` (synthetic
    sources), a ``lax.scan`` over K steps removes K-1 host dispatches per
    loop — decisive when the host↔chip link has high launch latency (e.g. a
    tunneled chip) and per-step dispatch would otherwise gate throughput.

    Numerics are mathematically identical to the per-step path — the step fn
    derives its RNG from ``state.step`` and the scan feeds each step the
    same ``gen_fn(key, step)`` batch ``source.batch(step)`` would have
    produced — but NOT bitwise: XLA fuses/reassociates the two programs
    differently (~1e-6/step fp drift, which BN+ReLU training chaotically
    amplifies; see tests/test_fused_loop.py).

    Returns ``runner(state, rng, start, n) -> (state, last_step_metrics)``
    with a per-``n`` compile cache, or None when ``train_step`` exposes no
    raw traceable step. ``start`` is traced, so every same-length block
    reuses one executable.
    """
    raw_step = getattr(train_step, "raw_step", None)
    gen_fn = getattr(source, "gen_fn", None)
    if raw_step is None or gen_fn is None:
        return None
    state_shardings = getattr(train_step, "state_shardings", None)
    rep = NamedSharding(mesh, P())

    def batch_constraint(batch):
        return jax.lax.with_sharding_constraint(
            batch, _batch_leaf_shardings(mesh, batch_shd, batch))

    def make(n: int):
        def fused(state, rng, key, start):
            def body(st, i):
                batch = batch_constraint(gen_fn(key, start + i))
                return raw_step(st, batch, rng)

            # Full unroll: a rolled while-loop body pins one conservative
            # layout for every iteration (XLA layout assignment can't
            # specialize across loop trips), measured 43% slower than
            # per-step dispatch for ResNet50; unrolled, XLA optimizes the
            # straight-line program like K consecutive steps.
            state2, stacked = jax.lax.scan(
                body, state, jnp.arange(n, dtype=jnp.int32), unroll=True)
            return state2, jax.tree_util.tree_map(lambda m: m[-1], stacked)

        kw = {}
        if state_shardings is not None:
            kw = dict(in_shardings=(state_shardings, rep, rep, rep),
                      out_shardings=(state_shardings, rep))
        return jax.jit(fused, donate_argnums=0, **kw)

    cache: dict[int, Any] = {}
    key = jax.random.key(source.seed)

    def runner(state, rng, start: int, n: int):
        if n not in cache:
            cache[n] = make(n)
        with use_mesh(mesh):
            return cache[n](state, rng, key, jnp.int32(start))

    return runner
