"""Checkpoint/resume via orbax — async, multi-host, sharding-aware.

The reference relied on framework-native rank-0 checkpoints
(tf.estimator / ``torch.save`` — SURVEY.md §5.4); the TPU-native replacement
is orbax's ``CheckpointManager``: every process participates in writing its
own shards of a ``jit``-laid-out ``TrainState`` (no gather to host 0), saves
are async (training continues while the previous state serializes), and
restore places shards directly onto the same mesh layout the step was
compiled for.

Failure semantics (SURVEY.md §5.3): a run that dies is restarted by the
launcher wrapper and resumes from ``latest_step`` — the fail-whole +
checkpoint-resume model the reference's mpirun jobs had, minus Batch-AI.

Optimizer-sharded states (any ZeRO stage) are saved through the CANONICAL
layout: ``zero.ZeroStateConverter`` gathers chunked leaves (opt state at
every stage; params/ema too at zero3) to replicated full shapes on save and
re-chunks on restore, so a checkpoint written at one stage/DP-degree resumes
at any other (tests/test_zero_ladder.py pins the matrix).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from distributeddeeplearning_tpu.config import TrainConfig


def _abstract_like(state: Any) -> Any:
    """ShapeDtypeStruct pytree carrying each leaf's current sharding, so
    orbax restores shards straight into the step's compiled layout."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state)


def device_copy(state: Any) -> Any:
    """Device-side copy of every array leaf: same sharding, NEW buffers,
    bitwise-identical contents (``jnp.copy`` — no arithmetic, so even
    ``-0.0`` signs survive). NOT ``device_put(x, x.sharding)``, which
    short-circuits to an alias of the same buffers and protects nothing."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state)


class _CorruptCheckpoint(Exception):
    """A step that orbax could not read back — corrupt or partially written.

    Deliberately wraps ONLY failures coming out of ``CheckpointManager
    .restore`` itself: policy errors raised by our own checks (EMA-flip
    rejection, structure/shape mismatches) are user-config problems and
    must propagate, never trigger quarantine of a perfectly good save."""

    def __init__(self, step: int, cause: BaseException):
        super().__init__(f"checkpoint step {step} failed to restore: "
                         f"{type(cause).__name__}: {cause}")
        self.step = step
        self.cause = cause


# How many corrupt steps restore will quarantine before giving up — bounds
# the cost of a directory full of damaged saves to a couple of retries.
_MAX_QUARANTINE = 2


class Checkpointer:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    Owns the save cadence (``checkpoint_every_steps``), keeps the last
    ``max_to_keep`` checkpoints, and exposes exactly the three operations the
    training loop needs: maybe_save / restore_latest / wait.

    ``converter`` (ZeRO-1 runs only) is a
    :class:`~distributeddeeplearning_tpu.parallel.zero.Zero1StateConverter`:
    saves gather the 1/N-sharded optimizer state into the CANONICAL layout
    (each leaf its parameter's shape, padding stripped — byte-identical to
    what a replicated run saves), restores reshard it back for the current
    layout. On-disk checkpoints therefore never depend on the run's
    optimizer-sharding mode or DP degree.
    """

    def __init__(self, directory: str, *, every_steps: int,
                 max_to_keep: int = 3, converter: Any = None):
        self.every_steps = max(int(every_steps), 1)
        self._converter = converter
        self._directory = os.path.abspath(directory)  # orbax rejects
        self._max_to_keep = max_to_keep               # relative paths
        self._mgr = self._make_manager()
        # Wall seconds the last successful restore_latest spent (None until
        # one runs). Feeds the elastic reconfiguration phase breakdown:
        # restore time vs compile time decides whether the overlap is
        # actually hiding anything.
        self.last_restore_s: Optional[float] = None

    def _make_manager(self) -> ocp.CheckpointManager:
        return ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                enable_async_checkpointing=True))

    @classmethod
    def create(cls, config: TrainConfig,
               converter: Any = None) -> Optional["Checkpointer"]:
        if not config.checkpoint_dir:
            return None
        return cls(config.checkpoint_dir,
                   every_steps=config.checkpoint_every_steps,
                   converter=converter)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def maybe_save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if ``step`` is on the cadence (or ``force``); skips steps
        already on disk so the final-step save never collides."""
        if not force and step % self.every_steps:
            return False
        if self._mgr.latest_step() == step:
            return False
        if self._converter is not None:
            # Gather-on-save: persist the canonical (mode/degree-agnostic)
            # optimizer-state layout.
            state = self._converter.to_canonical(state)
        if jax.default_backend() == "cpu":
            # Async-save snapshot safety — the save-side mirror of the
            # restore hazard device_copy guards in train/loop.py: on CPU
            # the checkpoint machinery's "device-to-host transfer" is a
            # zero-copy view of the live buffers, and the training loop
            # donates those same buffers to the next step. A cadence save
            # can then serialize already-overwritten memory in the
            # background thread (observed: garbage `step` scalars and
            # poisoned params in every non-final save of a multi-process
            # CPU run; only the final save — fenced by wait() — was
            # intact). Snapshot first: the copy's buffers belong to this
            # save alone. Accelerator backends do a real device-to-host
            # copy, so they skip the extra pass.
            state = device_copy(state)
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    # --- corrupt-step quarantine + fallback --------------------------------

    def _mgr_restore(self, step: int, args: Any) -> Any:
        """The ONE call site allowed to classify a failure as corruption:
        anything ``CheckpointManager.restore`` raises for a committed step
        means that step's bytes are unusable."""
        try:
            return self._mgr.restore(step, args=args)
        except Exception as e:
            raise _CorruptCheckpoint(step, e) from e

    def _with_fallback(self, restore_fn) -> Optional[Any]:
        """Run ``restore_fn(latest_step)``; on corruption, quarantine the
        step and retry the next-newest, up to ``_MAX_QUARANTINE`` times.
        Never silently falls through to a fresh start: a directory whose
        every checkpoint is damaged raises instead of discarding the run's
        history."""
        quarantined = 0
        while True:
            step = self._mgr.latest_step()
            if step is None:
                if quarantined:
                    raise RuntimeError(
                        f"no restorable checkpoint left in "
                        f"{self._directory} after quarantining "
                        f"{quarantined} corrupt step(s) (kept as corrupt.* "
                        f"for post-mortem); refusing to silently restart "
                        f"from scratch — delete the directory to do that "
                        f"deliberately")
                return None
            try:
                return restore_fn(step)
            except _CorruptCheckpoint as e:
                if quarantined >= _MAX_QUARANTINE:
                    raise e.cause
                self._quarantine(step, e.cause)
                quarantined += 1

    def _quarantine(self, step: int, err: BaseException) -> None:
        """Move a corrupt step dir aside (``corrupt.<step>`` — non-numeric,
        so orbax's latest_step never sees it again) with a loud warning."""
        import warnings

        src = os.path.join(self._directory, str(step))
        dst = os.path.join(self._directory, f"corrupt.{step}")
        warnings.warn(
            f"checkpoint step {step} failed to restore "
            f"({type(err).__name__}: {err}); quarantining it as {dst} and "
            f"falling back to the previous good checkpoint. This usually "
            f"means the save was cut short (preemption/disk) — inspect the "
            f"quarantined directory if it recurs.")
        if jax.process_index() == 0 and os.path.isdir(src):
            while os.path.exists(dst):
                dst += ".x"
            os.rename(src, dst)
        if jax.process_count() > 1:
            # Every process must see the rename before re-asking for
            # latest_step, or a fast process retries the same corrupt step.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"ddl:quarantine:{step}")
        self._reload()

    def _reload(self) -> None:
        """Refresh the manager's view of the directory after a quarantine
        rename (step caches vary by orbax version; recreate if needed)."""
        reload_fn = getattr(self._mgr, "reload", None)
        if callable(reload_fn):
            reload_fn()
            return
        self._mgr.close()
        self._mgr = self._make_manager()

    def restore_latest(self, state_like: Any) -> Optional[Any]:
        """Restore the newest checkpoint into ``state_like``'s layout, or
        None when the directory is empty (fresh run). A corrupt/partial
        newest step is quarantined (loud warning, dir renamed corrupt.N)
        and the previous good step restored instead.

        ``ema_params`` presence may legitimately differ from the checkpoint:
        ``--ema-decay`` can be turned on mid-experiment (resume a pre-EMA
        checkpoint) — the shadow is then seeded from the restored params,
        exactly how a fresh run seeds it from init. The reverse (checkpoint
        carries a trained EMA but the resume dropped the flag) is rejected
        loudly: silently discarding trained state contradicts the repo's
        dead-knob policy, and before this check it surfaced as an opaque
        orbax structure-mismatch error (ADVICE r3 #2)."""
        t0 = time.perf_counter()
        restored = self._with_fallback(
            lambda step: self._restore_latest_at(step, state_like))
        if restored is not None:
            self.last_restore_s = time.perf_counter() - t0
        return restored

    def _restore_latest_at(self, step: int, state_like: Any) -> Any:
        if self._converter is not None:
            # Restore targets the canonical on-disk layout (replicated),
            # then reshard-on-restore pads + scatters the optimizer state
            # back into the current run's chunked layout.
            state_like = self._converter.canonical_abstract(state_like)
        want_ema = state_like.ema_params is not None
        ckpt_ema = self._ckpt_has_ema(step)
        if ckpt_ema is None:  # unreadable metadata: keep the strict restore
            ckpt_ema = want_ema
        if ckpt_ema and not want_ema:
            raise ValueError(
                f"checkpoint step {step} carries EMA shadow params but this "
                f"run did not set --ema-decay. Resuming would silently drop "
                f"the trained EMA. Repeat the original --ema-decay to "
                f"continue it, or start a fresh --checkpoint-dir.")
        if want_ema and not ckpt_ema:
            import warnings

            warnings.warn(
                f"checkpoint step {step} predates --ema-decay: seeding the "
                f"EMA shadow from the restored params (the same way a fresh "
                f"run seeds it from init).")
            restored = self._mgr_restore(step, ocp.args.StandardRestore(
                _abstract_like(state_like.replace(ema_params=None))))
            restored = restored.replace(ema_params=restored.params)
            return self._from_canonical(restored)
        return self._from_canonical(self._mgr_restore(
            step, ocp.args.StandardRestore(_abstract_like(state_like))))

    def _from_canonical(self, restored: Any) -> Any:
        if self._converter is None:
            return restored
        return self._converter.from_canonical(restored)

    def _ckpt_has_ema(self, step: int) -> Optional[bool]:
        """Whether checkpoint ``step`` carries real EMA arrays, from the
        StandardSave ``_METADATA`` manifest on disk. (A fresh
        CheckpointManager's ``item_metadata`` cannot reconstruct the item
        without a handler registry in this orbax version — it returns a
        tree of None with an absl warning — so the file is the reliable
        source.) None = manifest unreadable; caller falls back to the
        strict structure-matched restore."""
        path = os.path.join(str(self._mgr.directory), str(step), "default",
                            "_METADATA")
        try:
            with open(path) as f:
                tree_meta = json.load(f)["tree_metadata"]
        except (OSError, ValueError, KeyError, TypeError) as e:
            # Visible degradation (ADVICE r4): an orbax upgrade that moves
            # or reshapes this private manifest must not SILENTLY demote
            # the friendly EMA-flip handling to the strict
            # structure-mismatch error path.
            import warnings

            warnings.warn(
                f"checkpoint manifest {path} unreadable "
                f"({type(e).__name__}: {e}); EMA-flip detection disabled "
                f"for this restore — falling back to strict "
                f"structure-matched restore (did an orbax upgrade change "
                f"the _METADATA layout?)")
            return None
        for key, entry in tree_meta.items():
            if key.startswith("('ema_params'"):
                # The None placeholder is a single ('ema_params',) entry of
                # value_type 'None'; real EMA shows array entries instead.
                value_type = entry.get("value_metadata", {}).get("value_type")
                if value_type not in ("None", None):
                    return True
        return False

    def _restore_subtree(self, raw_subtree: Any, like: Any, what: str) -> Any:
        """Unwrap serialized sharding boxes, check structure AND shapes
        against ``like``, and place leaves onto ``like``'s shardings."""
        from flax.core import meta

        # Sharding-metadata boxes (LogicallyPartitioned) serialize as
        # single-key {'value': leaf} dicts. Unwrap them by walking raw and
        # target in parallel: a {'value': leaf} dict is a box only where the
        # (unboxed) target tree has a LEAF at the same path — a model whose
        # submodule legitimately names a parameter 'value' has a dict there
        # in the target too, and is left alone (ADVICE r2 #3).
        like = meta.unbox(like)

        def _unwrap(raw, ref):
            if not isinstance(raw, dict):
                return raw
            if (set(raw) == {"value"} and not isinstance(raw["value"], dict)
                    and not isinstance(ref, dict)):
                return raw["value"]
            if isinstance(ref, dict):
                return {k: (_unwrap(v, ref[k]) if k in ref else v)
                        for k, v in raw.items()}
            return raw  # structure mismatch; the check below reports it

        tree = _unwrap(raw_subtree, like)
        if (jax.tree_util.tree_structure(tree)
                != jax.tree_util.tree_structure(like)):
            raise ValueError(
                f"checkpoint {what} structure does not match the model: "
                f"saved {jax.tree_util.tree_structure(tree)} vs expected "
                f"{jax.tree_util.tree_structure(like)}")

        def place(arr, ref):
            if tuple(arr.shape) != tuple(ref.shape):
                raise ValueError(
                    f"checkpoint {what} shape mismatch: saved {arr.shape} "
                    f"vs model {ref.shape} — e.g. a position table trained "
                    f"at a shorter context; rebuild the model to match the "
                    f"checkpoint (seq_len / max-new-tokens)")
            return jax.device_put(arr, ref.sharding)

        return jax.tree_util.tree_map(place, tree, like)

    def restore_latest_params(self, params_like: Any) -> Optional[Any]:
        """Restore ONLY the model parameters from the newest checkpoint.

        For consumers that don't train (generate.py): the optimizer state's
        structure depends on the training run's optimizer choice, which a
        sampler neither knows nor needs. Uses a raw (target-less) restore —
        this orbax version has no partial StandardRestore — so the whole
        tree loads to host once; sampler-scale only."""
        return self._with_fallback(
            lambda step: self._restore_subtree(
                self._restore_raw(step)["params"], params_like, "params"))

    def _restore_raw(self, step: int) -> Any:
        """Target-less restore of the raw checkpoint tree (host arrays).
        This orbax version's ``restore(step)`` with no args needs a handler
        registry to reconstruct the item; the explicit empty
        ``StandardRestore`` asks for the tree as saved instead."""
        return self._mgr_restore(step, ocp.args.StandardRestore())

    def restore_latest_for_eval(self, state_like: Any) -> Optional[Any]:
        """Restore params + BN statistics + step — everything inference
        needs — keeping ``state_like``'s (fresh) optimizer state, so
        eval-only runs don't have to repeat the training run's optimizer
        flags to satisfy a StandardRestore structure match."""
        return self._with_fallback(
            lambda step: self._restore_for_eval_at(step, state_like))

    def _restore_for_eval_at(self, step: int, state_like: Any) -> Any:
        import jax.numpy as jnp

        restored = self._restore_raw(step)
        params = self._restore_subtree(restored["params"], state_like.params,
                                       "params")
        batch_stats = state_like.batch_stats
        if batch_stats is not None:
            batch_stats = self._restore_subtree(
                restored["batch_stats"], batch_stats, "batch_stats")
        # EMA shadow params follow the CHECKPOINT, not the flag: if the
        # training run kept an EMA, eval-only scores it (the documented
        # contract) whether or not --ema-decay was repeated; if it did not,
        # a fresh-init EMA from the flag must not shadow the trained params.
        ema = restored.get("ema_params")
        ema = (self._restore_subtree(ema, state_like.params, "ema_params")
               if ema is not None else None)
        return state_like.replace(
            step=jnp.asarray(restored["step"], jnp.int32),
            params=params, batch_stats=batch_stats, ema_params=ema)

    def verify_or_record_stream_meta(self, meta: dict,
                                     update: Optional[dict] = None) -> dict:
        """Pin environment-dependent data-stream facts (e.g. the resolved
        ``auto`` loader) to the checkpoint directory.

        First run records ``meta``; a resumed run whose resolution differs
        (say the C++ toolchain vanished and auto now picks tf.data, whose
        shuffle order differs) fails loudly instead of silently feeding a
        different sample stream than the one the checkpoint was trained on
        (ADVICE r1 #1). Pass the loader explicitly to override.

        ``update`` keys are INFORMATIONAL: recorded and rewritten every run,
        never clash-checked. The elastic launcher uses this for
        ``mesh_degree`` — the degree legitimately changes across a
        re-formation, but the loop wants the previous run's value to report
        a cross-degree resume. Returns the previously recorded dict (empty
        on a fresh directory), read BEFORE this run's rewrite.
        """
        # Multi-host: agree BEFORE touching the file. Only process 0 writes,
        # so on a heterogeneous pod a non-zero process that resolved a
        # different loader would otherwise go unchecked whenever its read
        # races ahead of process 0's write (VERDICT r2 Weak #6). A collective
        # fingerprint comparison enforces the within-run invariant directly;
        # the file then only carries it across runs.
        full = dict(meta, **(update or {}))
        self._assert_uniform_across_processes(full)
        path = os.path.join(self._mgr.directory, "stream_meta.json")
        recorded: dict = {}
        if os.path.exists(path):
            with open(path) as f:
                recorded = json.load(f)
            clashes = {k: (recorded[k], v) for k, v in meta.items()
                       if k in recorded and recorded[k] != v}
            if clashes:
                raise RuntimeError(
                    f"checkpoint stream metadata mismatch in {path}: "
                    + "; ".join(
                        f"{k}: recorded {old!r}, this run resolved {new!r}"
                        for k, (old, new) in clashes.items())
                    + ". Resuming with a different data pipeline would "
                    "change the post-resume sample stream. Set the field "
                    "explicitly (e.g. --loader) to match the original run, "
                    "or start a fresh checkpoint_dir.")
        if jax.process_index() == 0 and (not recorded
                                         or any(recorded.get(k) != v
                                                for k, v in full.items())):
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(dict(recorded, **full), f)
            os.replace(tmp, path)
        return recorded

    @staticmethod
    def _assert_uniform_across_processes(meta: dict) -> None:
        if jax.process_count() == 1:
            return
        import hashlib

        import numpy as np
        from jax.experimental import multihost_utils

        digest = hashlib.sha256(
            json.dumps(meta, sort_keys=True).encode()).digest()[:16]
        mine = np.frombuffer(digest, np.uint32)
        all_ = np.asarray(multihost_utils.process_allgather(mine))
        if not (all_ == all_[0]).all():
            bad = [i for i in range(all_.shape[0])
                   if not (all_[i] == all_[0]).all()]
            raise RuntimeError(
                f"data-stream metadata differs across processes (e.g. a "
                f"heterogeneous pod resolved different loaders): this "
                f"process {jax.process_index()} vs processes {bad[:8]}. "
                f"Set the pipeline explicitly (e.g. --loader) so every "
                f"host resolves identically. Local meta: {meta!r}")

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
