"""Checkpoint/resume via orbax — async, multi-host, sharding-aware.

The reference relied on framework-native rank-0 checkpoints
(tf.estimator / ``torch.save`` — SURVEY.md §5.4); the TPU-native replacement
is orbax's ``CheckpointManager``: every process participates in writing its
own shards of a ``jit``-laid-out ``TrainState`` (no gather to host 0), saves
are async (training continues while the previous state serializes), and
restore places shards directly onto the same mesh layout the step was
compiled for.

Failure semantics (SURVEY.md §5.3): a run that dies is restarted by the
launcher wrapper and resumes from ``latest_step`` — the fail-whole +
checkpoint-resume model the reference's mpirun jobs had, minus Batch-AI.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from distributeddeeplearning_tpu.config import TrainConfig


def _abstract_like(state: Any) -> Any:
    """ShapeDtypeStruct pytree carrying each leaf's current sharding, so
    orbax restores shards straight into the step's compiled layout."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state)


class Checkpointer:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    Owns the save cadence (``checkpoint_every_steps``), keeps the last
    ``max_to_keep`` checkpoints, and exposes exactly the three operations the
    training loop needs: maybe_save / restore_latest / wait.
    """

    def __init__(self, directory: str, *, every_steps: int,
                 max_to_keep: int = 3):
        self.every_steps = max(int(every_steps), 1)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),  # orbax rejects relative paths
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True))

    @classmethod
    def create(cls, config: TrainConfig) -> Optional["Checkpointer"]:
        if not config.checkpoint_dir:
            return None
        return cls(config.checkpoint_dir,
                   every_steps=config.checkpoint_every_steps)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def maybe_save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if ``step`` is on the cadence (or ``force``); skips steps
        already on disk so the final-step save never collides."""
        if not force and step % self.every_steps:
            return False
        if self._mgr.latest_step() == step:
            return False
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore_latest(self, state_like: Any) -> Optional[Any]:
        """Restore the newest checkpoint into ``state_like``'s layout, or
        None when the directory is empty (fresh run)."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(_abstract_like(state_like)))

    def verify_or_record_stream_meta(self, meta: dict) -> None:
        """Pin environment-dependent data-stream facts (e.g. the resolved
        ``auto`` loader) to the checkpoint directory.

        First run records ``meta``; a resumed run whose resolution differs
        (say the C++ toolchain vanished and auto now picks tf.data, whose
        shuffle order differs) fails loudly instead of silently feeding a
        different sample stream than the one the checkpoint was trained on
        (ADVICE r1 #1). Pass the loader explicitly to override.
        """
        path = os.path.join(self._mgr.directory, "stream_meta.json")
        if os.path.exists(path):
            with open(path) as f:
                recorded = json.load(f)
            clashes = {k: (recorded[k], v) for k, v in meta.items()
                       if k in recorded and recorded[k] != v}
            if clashes:
                raise RuntimeError(
                    f"checkpoint stream metadata mismatch in {path}: "
                    + "; ".join(
                        f"{k}: recorded {old!r}, this run resolved {new!r}"
                        for k, (old, new) in clashes.items())
                    + ". Resuming with a different data pipeline would "
                    "change the post-resume sample stream. Set the field "
                    "explicitly (e.g. --loader) to match the original run, "
                    "or start a fresh checkpoint_dir.")
        elif jax.process_index() == 0:
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, path)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
