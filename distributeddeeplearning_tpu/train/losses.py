"""Loss functions shared by the trainers.

Float32 loss math regardless of compute dtype (logits are emitted f32 by
every model in the zoo) — bf16 softmax/CE is where mixed-precision training
silently loses accuracy, so it stays full precision.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def smoothed_softmax_ce(logits: jnp.ndarray, labels: jnp.ndarray,
                        smoothing: float = 0.1) -> jnp.ndarray:
    """Label-smoothed cross entropy, mean over the batch. (B,C) x (B,) -> ()."""
    num_classes = logits.shape[-1]
    if smoothing:
        one_hot = optax.smooth_labels(
            jnp.eye(num_classes, dtype=jnp.float32)[labels], smoothing)
        loss = optax.softmax_cross_entropy(logits, one_hot)
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return loss.mean()


def top1_accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return (jnp.argmax(logits, -1) == labels).astype(jnp.float32).mean()


def mlm_loss_sums(logits: jnp.ndarray, labels: jnp.ndarray):
    """(sum of per-token CE over masked positions, masked-position count).

    ``labels`` is (B, S) int32 with -1 at unmasked positions (the ignore
    index). The sum form aggregates exactly across shards/batches (eval
    perplexity); :func:`mlm_loss` is its mean.
    """
    weights = (labels >= 0).astype(jnp.float32)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(labels, 0))
    return (per_tok * weights).sum(), weights.sum()


def mlm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Masked-LM cross entropy: mean over masked positions, guarded
    against an all-unmasked batch."""
    total, count = mlm_loss_sums(logits, labels)
    return total / jnp.maximum(count, 1.0)


def causal_lm_loss_sums(logits: jnp.ndarray, input_ids: jnp.ndarray,
                        attention_mask: jnp.ndarray | None = None):
    """(sum of next-token CE, predicted-token count): logits[:, t] predicts
    input_ids[:, t+1].

    Both sides of the shift must be real tokens: a padded *query* position
    produces a garbage (uniform-over-everything) logit row, so its
    prediction must not be scored even when the target is real.
    """
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], input_ids[:, 1:])
    if attention_mask is None:
        weights = jnp.ones(per_tok.shape, jnp.float32)
    else:
        mask = attention_mask.astype(jnp.float32)
        weights = mask[:, :-1] * mask[:, 1:]
    return (per_tok * weights).sum(), weights.sum()


def causal_lm_loss(logits: jnp.ndarray, input_ids: jnp.ndarray,
                   attention_mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Next-token cross entropy, mean over predicted tokens."""
    total, count = causal_lm_loss_sums(logits, input_ids, attention_mask)
    return total / jnp.maximum(count, 1.0)
