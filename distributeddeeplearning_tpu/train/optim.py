"""Optimizers + LR schedules (optax).

Covers the acceptance matrix: SGD-momentum for the ResNet/DenseNet DP configs
(BASELINE.json:7-9), AdamW for BERT MLM (BASELINE.json:10), LARS with the
linear-scaling + warmup + polynomial-decay recipe for batch=32k
(BASELINE.json:11; recipe per PAPERS.md:8-9 large-batch papers), and LAMB
for large-batch BERT.

Weight decay is masked off BatchNorm/LayerNorm parameters and biases — the
standard large-batch convention; for LARS the same mask also disables the
trust-ratio rescaling on those leaves.

ZeRO sharding (``shard_axes``): under any stage of the optimizer-sharding
ladder (parallel/zero.py, zero1/zero2/zero3) the transformation sees each
leaf's 1/N *chunk* instead of the full leaf — the stages differ only in
how grads/params are MOVED around the update, never in what the update
math sees.
Elementwise transforms (momentum, Adam moments, decoupled weight decay)
are unaffected — same treedef, same per-element math, zero padding inert.
Only NORMS see partial data, so the two norm consumers get sharded mirrors
here: global-norm clipping and the LARS/LAMB per-leaf trust ratios compute
``sqrt(psum(sum(x^2)))`` over the DP axes, reproducing the full-leaf norm
exactly (up to fp summation order).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax
import jax
import jax.numpy as jnp
import optax

from distributeddeeplearning_tpu.config import OptimizerConfig


def _decay_mask(params: Any) -> Any:
    """True for leaves that get weight decay: kernels/embeddings only.

    Accepts frozen or plain nests uniformly and returns a mask with the
    SAME treedef as the input — optax's masking zips mask and update trees,
    so a plain-dict mask over FrozenDict params is a structure mismatch.
    The mask keys on leaf *names*, which ZeRO-1 chunking preserves (the
    chunk tree has the parameter treedef), so one mask serves both layouts.
    """
    frozen = isinstance(params, flax.core.FrozenDict)
    flat = flax.traverse_util.flatten_dict(
        flax.core.unfreeze(params) if frozen else params)
    mask = {
        path: (path[-1] == "kernel" or "embedding" in path[-1])
        for path in flat
    }
    mask = flax.traverse_util.unflatten_dict(mask)
    return flax.core.freeze(mask) if frozen else mask


def scaled_lr(cfg: OptimizerConfig, global_batch: int) -> float:
    """Linear-scaling rule: lr = base_lr * batch / reference_batch."""
    return cfg.learning_rate * global_batch / cfg.reference_batch


# ---------------------------------------------------------------------------
# Staged global-batch ramp (arXiv 1711.04325: "Extremely Large Minibatch
# SGD" ramps 8k -> 32k mid-run with the LR following the linear-scaling
# rule). The ramp is pure host-side orchestration: train/loop.run splits the
# horizon into stages, each stage a normal run segment at its own
# global_batch_size (LR scaled per stage by the existing scaled_lr rule)
# that resumes from the previous stage's checkpoint. Because every boundary
# is forced onto the checkpoint cadence, elastic re-formation and
# cross-degree resume inside a stage compose unchanged — a boundary IS a
# checkpoint/restore, the one transition those paths already handle.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RampStage:
    """One stage of a staged batch ramp: run ``[start_step, end_step)`` at
    ``batch`` examples per optimizer step (``end_step=None`` = to the
    horizon)."""

    batch: int
    start_step: int
    end_step: Optional[int]


def parse_batch_ramp(spec: Optional[str], *, final_batch: int,
                     checkpoint_every: int) -> Optional[list[RampStage]]:
    """Parse a ``batch:steps,...,batch`` ramp spec into stages.

    ``"8192:600,16384:600,32768"`` = 600 steps at 8192, 600 at 16384, then
    32768 to the horizon. Validation is strict and happens up front — a
    malformed ramp must die before any backend init:

    - every stage but the last carries an explicit step count; the last
      must not (it runs to the horizon);
    - the last stage's batch must equal ``final_batch`` (the config's
      ``global_batch_size`` — the ramp describes how to REACH it);
    - batches must be positive and non-decreasing;
    - every boundary must be a multiple of ``checkpoint_every`` so each
      stage transition rides an existing checkpoint save/restore.

    Returns None for an absent spec or a degenerate single-stage ramp at
    the final batch (both mean: no ramp orchestration needed).
    """
    if not spec:
        return None
    stages: list[RampStage] = []
    parts = [s.strip() for s in spec.split(",") if s.strip()]
    if not parts:
        raise ValueError(f"batch_ramp {spec!r}: empty spec")
    step = 0
    for i, part in enumerate(parts):
        last = i == len(parts) - 1
        if ":" in part:
            if last:
                raise ValueError(
                    f"batch_ramp {spec!r}: the last stage must not carry a "
                    f"step count (it runs to the horizon)")
            b_str, n_str = part.split(":", 1)
            try:
                batch, n = int(b_str), int(n_str)
            except ValueError:
                raise ValueError(f"batch_ramp {spec!r}: stage {part!r} is "
                                 f"not 'batch:steps'") from None
            if n < 1:
                raise ValueError(f"batch_ramp {spec!r}: stage {part!r} must "
                                 f"run >= 1 step")
            stages.append(RampStage(batch=batch, start_step=step,
                                    end_step=step + n))
            step += n
        else:
            if not last:
                raise ValueError(
                    f"batch_ramp {spec!r}: only the last stage may omit "
                    f":steps (got {part!r} at position {i})")
            try:
                batch = int(part)
            except ValueError:
                raise ValueError(f"batch_ramp {spec!r}: stage {part!r} is "
                                 f"not an int batch") from None
            stages.append(RampStage(batch=batch, start_step=step,
                                    end_step=None))
    for st in stages:
        if st.batch < 1:
            raise ValueError(f"batch_ramp {spec!r}: batch {st.batch} < 1")
    for a, b in zip(stages, stages[1:]):
        if b.batch < a.batch:
            raise ValueError(
                f"batch_ramp {spec!r}: batches must be non-decreasing "
                f"(got {a.batch} -> {b.batch}); a ramp shrinks the step "
                f"count, never the batch")
    if stages[-1].batch != final_batch:
        raise ValueError(
            f"batch_ramp {spec!r}: final stage batch {stages[-1].batch} != "
            f"global_batch_size {final_batch} — the ramp describes how to "
            f"reach the configured batch, not a different one")
    if checkpoint_every > 0:
        for st in stages[:-1]:
            if st.end_step % checkpoint_every:
                raise ValueError(
                    f"batch_ramp {spec!r}: boundary at step {st.end_step} "
                    f"is not a multiple of checkpoint_every_steps="
                    f"{checkpoint_every} — stage transitions must ride an "
                    f"existing checkpoint save so resume and elastic "
                    f"re-formation compose unchanged")
    if len(stages) == 1:
        return None  # degenerate: already at the final batch the whole run
    return stages


def ramp_final_batch(config) -> int:
    """The batch the run ends at: ``global_batch_size`` normally; under a
    mid-ramp stage segment (where loop.run rewrote global_batch_size to the
    stage batch) still the ramp's final batch. This is the value the
    checkpoint stream-meta pins, so every stage of one ramp — and a plain
    resume at the final batch — agree on it."""
    spec = getattr(config, "batch_ramp", None)
    if not spec:
        return config.global_batch_size
    last = [s.strip() for s in spec.split(",") if s.strip()][-1]
    try:
        return int(last.split(":", 1)[0])
    except ValueError:
        return config.global_batch_size


def ramp_describe(config) -> str:
    """Provenance tag for perf records: the ramp spec or ``none``."""
    return getattr(config, "batch_ramp", None) or "none"


def make_schedule(cfg: OptimizerConfig, global_batch: int,
                  total_steps: int,
                  steps_per_epoch: Optional[int] = None) -> optax.Schedule:
    peak = scaled_lr(cfg, global_batch)
    warmup = int(cfg.warmup_epochs * steps_per_epoch) if steps_per_epoch \
        else max(int(0.05 * total_steps), 1)
    warmup = min(warmup, max(total_steps - 1, 1))
    if cfg.schedule == "constant":
        return optax.constant_schedule(peak)
    if cfg.schedule == "linear":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak, warmup),
             optax.linear_schedule(peak, 0.0, max(total_steps - warmup, 1))],
            [warmup])
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak, warmup_steps=warmup,
            decay_steps=max(total_steps, warmup + 1))
    if cfg.schedule == "warmup_poly":
        # LARS paper recipe: warmup then polynomial (power-2) decay to 0.
        poly = optax.polynomial_schedule(
            init_value=peak, end_value=0.0, power=2,
            transition_steps=max(total_steps - warmup, 1))
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak, warmup), poly], [warmup])
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


# ---------------------------------------------------------------------------
# Sharded-norm mirrors of optax's two norm consumers. Formula-identical to
# optax.scale_by_trust_ratio / optax.clip_by_global_norm, with every
# sum-of-squares psum'd over `axes` so each shard's partial leaf yields the
# full-leaf norm. MUST be called inside shard_map over `axes`.
# ---------------------------------------------------------------------------

def _sharded_norm(x, axes) -> jax.Array:
    return jnp.sqrt(jax.lax.psum(jnp.sum(jnp.square(x)), axes))


def scale_by_trust_ratio_sharded(
        axes, trust_coefficient: float = 1.0,
        eps: float = 0.0) -> optax.GradientTransformation:
    """optax.scale_by_trust_ratio over leaves sharded along ``axes``."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("scale_by_trust_ratio_sharded requires params")

        def _scale_update(update, param):
            # Mirrors optax: zero-norm params/updates fall back to ratio 1.
            param_norm = _sharded_norm(param, axes)
            update_norm = _sharded_norm(update, axes)
            trust_ratio = trust_coefficient * param_norm / (update_norm + eps)
            zero_norm = jnp.logical_or(param_norm == 0.0, update_norm == 0.0)
            safe_trust_ratio = jnp.where(
                zero_norm, jnp.array(1.0, dtype=param.dtype), trust_ratio)
            return update * safe_trust_ratio

        updates = jax.tree_util.tree_map(_scale_update, updates, params)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def clip_by_global_norm_sharded(max_norm: float,
                                axes) -> optax.GradientTransformation:
    """optax.clip_by_global_norm with the global norm psum'd over ``axes``."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        sq = sum(jnp.sum(jnp.square(u))
                 for u in jax.tree_util.tree_leaves(updates))
        g_norm = jnp.sqrt(jax.lax.psum(sq, axes))
        trigger = jnp.squeeze(g_norm < max_norm)

        def clip_fn(t):
            return jax.lax.select(
                trigger, t, (t / g_norm.astype(t.dtype)) * max_norm)

        return jax.tree_util.tree_map(clip_fn, updates), state

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(cfg: OptimizerConfig, global_batch: int, total_steps: int,
                   steps_per_epoch: Optional[int] = None,
                   shard_axes=None
                   ) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Build the optimizer chain. ``shard_axes`` (ZeRO-1 only) names the
    mesh axes the parameter chunks are sharded over; norm-based pieces then
    use the sharded mirrors above, every elementwise piece is reused
    verbatim, and the chain ORDER matches optax's stock composites exactly
    so replicated and zero1 trajectories agree per element."""
    if not 0.0 <= cfg.ema_decay < 1.0:
        raise ValueError(
            f"ema_decay={cfg.ema_decay}: need 0 <= decay < 1 "
            f"(1.0 would freeze the shadow params at init "
            f"forever; evals would score random weights)")
    sched = make_schedule(cfg, global_batch, total_steps, steps_per_epoch)
    if cfg.name == "sgd":
        tx = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
            optax.sgd(sched, momentum=cfg.momentum, nesterov=False),
        )
    elif cfg.name == "lars":
        if shard_axes is None:
            tx = optax.lars(
                sched, weight_decay=cfg.weight_decay,
                weight_decay_mask=_decay_mask,
                trust_coefficient=cfg.trust_coefficient,
                trust_ratio_mask=_decay_mask,
                momentum=cfg.momentum)
        else:
            # optax.lars's exact chain with the trust-ratio norm psum'd.
            tx = optax.chain(
                optax.add_decayed_weights(cfg.weight_decay,
                                          mask=_decay_mask),
                optax.masked(
                    scale_by_trust_ratio_sharded(
                        shard_axes,
                        trust_coefficient=cfg.trust_coefficient),
                    mask=_decay_mask),
                optax.scale_by_learning_rate(sched),
                optax.trace(decay=cfg.momentum, nesterov=False),
            )
    elif cfg.name == "adamw":
        tx = optax.adamw(
            sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
            weight_decay=cfg.weight_decay, mask=_decay_mask)
    elif cfg.name == "lamb":
        # Layer-wise Adam (You et al.) — the canonical large-batch BERT
        # optimizer, completing the pod-scale pair with LARS (CNNs).
        if shard_axes is None:
            tx = optax.lamb(
                sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
                weight_decay=cfg.weight_decay, mask=_decay_mask)
        else:
            # optax.lamb's exact chain with the trust-ratio norm psum'd.
            tx = optax.chain(
                optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps),
                optax.add_decayed_weights(cfg.weight_decay,
                                          mask=_decay_mask),
                scale_by_trust_ratio_sharded(shard_axes),
                optax.scale_by_learning_rate(sched),
            )
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if cfg.grad_clip_norm:
        clip = (optax.clip_by_global_norm(cfg.grad_clip_norm)
                if shard_axes is None
                else clip_by_global_norm_sharded(cfg.grad_clip_norm,
                                                 shard_axes))
        tx = optax.chain(clip, tx)
    return tx, sched
