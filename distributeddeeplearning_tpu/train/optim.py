"""Optimizers + LR schedules (optax).

Covers the acceptance matrix: SGD-momentum for the ResNet/DenseNet DP configs
(BASELINE.json:7-9), AdamW for BERT MLM (BASELINE.json:10), LARS with the
linear-scaling + warmup + polynomial-decay recipe for batch=32k
(BASELINE.json:11; recipe per PAPERS.md:8-9 large-batch papers), and LAMB
for large-batch BERT.

Weight decay is masked off BatchNorm/LayerNorm parameters and biases — the
standard large-batch convention; for LARS the same mask also disables the
trust-ratio rescaling on those leaves.
"""

from __future__ import annotations

from typing import Any, Optional

import flax
import jax.numpy as jnp
import optax

from distributeddeeplearning_tpu.config import OptimizerConfig


def _decay_mask(params: Any) -> Any:
    """True for leaves that get weight decay: kernels/embeddings only."""
    flat = flax.traverse_util.flatten_dict(params)
    mask = {
        path: (path[-1] == "kernel" or "embedding" in path[-1])
        for path in flat
    }
    return flax.traverse_util.unflatten_dict(mask)


def scaled_lr(cfg: OptimizerConfig, global_batch: int) -> float:
    """Linear-scaling rule: lr = base_lr * batch / reference_batch."""
    return cfg.learning_rate * global_batch / cfg.reference_batch


def make_schedule(cfg: OptimizerConfig, global_batch: int,
                  total_steps: int,
                  steps_per_epoch: Optional[int] = None) -> optax.Schedule:
    peak = scaled_lr(cfg, global_batch)
    warmup = int(cfg.warmup_epochs * steps_per_epoch) if steps_per_epoch \
        else max(int(0.05 * total_steps), 1)
    warmup = min(warmup, max(total_steps - 1, 1))
    if cfg.schedule == "constant":
        return optax.constant_schedule(peak)
    if cfg.schedule == "linear":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak, warmup),
             optax.linear_schedule(peak, 0.0, max(total_steps - warmup, 1))],
            [warmup])
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak, warmup_steps=warmup,
            decay_steps=max(total_steps, warmup + 1))
    if cfg.schedule == "warmup_poly":
        # LARS paper recipe: warmup then polynomial (power-2) decay to 0.
        poly = optax.polynomial_schedule(
            init_value=peak, end_value=0.0, power=2,
            transition_steps=max(total_steps - warmup, 1))
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak, warmup), poly], [warmup])
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def make_optimizer(cfg: OptimizerConfig, global_batch: int, total_steps: int,
                   steps_per_epoch: Optional[int] = None
                   ) -> tuple[optax.GradientTransformation, optax.Schedule]:
    if not 0.0 <= cfg.ema_decay < 1.0:
        raise ValueError(
            f"ema_decay={cfg.ema_decay}: need 0 <= decay < 1 "
            f"(1.0 would freeze the shadow params at init "
            f"forever; evals would score random weights)")
    sched = make_schedule(cfg, global_batch, total_steps, steps_per_epoch)
    if cfg.name == "sgd":
        tx = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
            optax.sgd(sched, momentum=cfg.momentum, nesterov=False),
        )
    elif cfg.name == "lars":
        tx = optax.lars(
            sched, weight_decay=cfg.weight_decay,
            weight_decay_mask=_decay_mask,
            trust_coefficient=cfg.trust_coefficient,
            trust_ratio_mask=_decay_mask,
            momentum=cfg.momentum)
    elif cfg.name == "adamw":
        tx = optax.adamw(
            sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
            weight_decay=cfg.weight_decay, mask=_decay_mask)
    elif cfg.name == "lamb":
        # Layer-wise Adam (You et al.) — the canonical large-batch BERT
        # optimizer, completing the pod-scale pair with LARS (CNNs).
        tx = optax.lamb(
            sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
            weight_decay=cfg.weight_decay, mask=_decay_mask)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if cfg.grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    return tx, sched
