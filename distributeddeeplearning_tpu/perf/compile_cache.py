"""Shared persistent compile-cache policy for every entry point.

One resolution order, everywhere::

    explicit flag/config value  >  $DDL_COMPILE_CACHE  >  <repo>/.cache/jax_compile

``"off"`` (or ``"none"``/``"0"``/``"disabled"``/empty) at any level disables
caching outright. ``activate()`` points JAX's persistent compilation cache at
the resolved directory and re-exports it through the environment
(``DDL_COMPILE_CACHE`` + ``JAX_COMPILATION_CACHE_DIR``) so launcher children
and every ``DDL_RESTART_ATTEMPT`` inherit the same cache without replumbing.

The cache is an optimization, never a dependency: every failure path here
degrades to "no cache" with a warning instead of raising. This module stays
importable without jax (launch.py runs on hosts before jax is initialized);
jax is imported lazily inside ``activate()`` only.

Hit/miss counters for the AOT executable layer (perf/aot.py) are persisted
to ``<cache_dir>/ddl_cache_stats.json`` so ``tools/doctor.py`` can report
the last run's cache behaviour after the fact.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Optional

ENV_CACHE = "DDL_COMPILE_CACHE"
STATS_FILE = "ddl_cache_stats.json"
AOT_SUBDIR = "aot"
_OFF_VALUES = frozenset({"off", "none", "0", "disabled", ""})


def default_dir() -> str:
    """Repo-local default: ``<repo>/.cache/jax_compile`` (the directory
    bench.py historically used privately, now shared by all entry points)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".cache", "jax_compile")


def resolve_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the cache directory (flag > env > default); None = disabled."""
    value = explicit if explicit is not None else os.environ.get(ENV_CACHE)
    if value is None:
        return default_dir()
    if value.strip().lower() in _OFF_VALUES:
        return None
    return os.path.abspath(os.path.expanduser(value))


def export_env(path: Optional[str]) -> None:
    """Export the resolved cache dir so child processes (launcher spawns,
    restart attempts) land on the same cache. jax-free, launcher-safe."""
    if path is None:
        os.environ[ENV_CACHE] = "off"
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    else:
        os.environ[ENV_CACHE] = path
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path


def activate(explicit: Optional[str] = None, *,
             export: bool = True) -> Optional[str]:
    """Enable JAX's persistent compilation cache at the resolved directory.

    Returns the active cache dir, or None when disabled / unavailable.
    Never raises: the cache is an optimization, not a dependency.
    """
    path = resolve_dir(explicit)
    if path is None:
        if export:
            export_env(None)
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # jax gates the persistent cache behind a minimum compile time /
        # entry size meant for interactive GPU use; a training step is
        # always worth caching, and the CPU test path must exercise the
        # same machinery the TPU path uses. Knobs vary across jax
        # versions, so each is best-effort.
        for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, value)
            except Exception:
                pass
    except Exception as exc:  # noqa: BLE001 - degrade, never fail the run
        print(f"[compile_cache] disabled ({type(exc).__name__}: {exc})",
              file=sys.stderr)
        return None
    if export:
        export_env(path)
    return path


# ---------------------------------------------------------------------------
# Introspection for tools/doctor.py and run summaries.
# ---------------------------------------------------------------------------

def summarize(path: Optional[str] = None) -> dict[str, Any]:
    """Entry count / total size for a cache directory (0s when absent)."""
    path = resolve_dir(path) if path is None else path
    out: dict[str, Any] = {"dir": path, "entries": 0, "aot_entries": 0,
                           "total_bytes": 0}
    if not path or not os.path.isdir(path):
        return out
    for root, _dirs, files in os.walk(path):
        for name in files:
            if name == STATS_FILE:
                continue
            full = os.path.join(root, name)
            try:
                out["total_bytes"] += os.path.getsize(full)
            except OSError:
                continue
            if os.path.basename(root) == AOT_SUBDIR:
                out["aot_entries"] += 1
            else:
                out["entries"] += 1
    return out


def _stats_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, STATS_FILE)


def write_stats(cache_dir: Optional[str], stats: dict[str, Any]) -> None:
    """Persist last-run counters (best-effort; last writer wins)."""
    if not cache_dir:
        return
    try:
        payload = dict(stats)
        payload["updated_at"] = time.time()
        payload["pid"] = os.getpid()
        tmp = _stats_path(cache_dir) + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, _stats_path(cache_dir))
    except Exception:  # noqa: BLE001
        pass


def read_stats(cache_dir: Optional[str] = None) -> Optional[dict[str, Any]]:
    cache_dir = resolve_dir(None) if cache_dir is None else cache_dir
    if not cache_dir:
        return None
    try:
        with open(_stats_path(cache_dir)) as fh:
            return json.load(fh)
    except Exception:  # noqa: BLE001
        return None


def prune(cache_dir: Optional[str] = None, *,
          max_age_days: float = 30.0) -> tuple[int, int]:
    """Delete cache entries older than ``max_age_days`` (by mtime).

    Returns ``(removed, kept)``. Safe on a live cache: jax re-creates
    entries on miss, and the AOT layer treats a vanished file as a miss.
    """
    cache_dir = resolve_dir(None) if cache_dir is None else cache_dir
    removed = kept = 0
    if not cache_dir or not os.path.isdir(cache_dir):
        return removed, kept
    cutoff = time.time() - max_age_days * 86400.0
    for root, _dirs, files in os.walk(cache_dir):
        for name in files:
            if name == STATS_FILE:
                continue
            full = os.path.join(root, name)
            try:
                if os.path.getmtime(full) < cutoff:
                    os.remove(full)
                    removed += 1
                else:
                    kept += 1
            except OSError:
                continue
    return removed, kept
