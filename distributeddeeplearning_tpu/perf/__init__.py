"""Compilation-latency subsystem: persistent compile cache + AOT executables.

Cold-start and recovery latency are dominated by XLA compilation we already
paid for on a previous run (or a previous restart attempt). This package
makes compilation a cached, observable resource:

- ``compile_cache`` — one shared persistent-cache policy (directory layout,
  env/flag plumbing, hit/miss counters) used by train.py, bench.py, and
  launch.py, and inherited by every spawned child and restart attempt.
- ``aot`` — ahead-of-time ``lower().compile()`` of the train/eval step
  keyed by a stable config fingerprint, with serialized-executable
  save/load so a warm restart skips tracing entirely.

Both layers are strictly wall-clock optimizations: a cache hit loads the
same XLA program a cold compile would produce, so numerics (including the
zero1<->replicated and chaos-soak bitwise pins) are unaffected.
"""
