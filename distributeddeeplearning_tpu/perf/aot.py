"""Ahead-of-time step executables keyed by a stable config fingerprint.

A restart attempt (launch.run_with_restarts) and a re-launch of the same
config pay the largest fixed cost of the run again: tracing + XLA-compiling
the train step. This module removes that cost end to end:

- ``config_fingerprint`` hashes exactly the parts of a ``TrainConfig`` that
  reach the compiled program (model, topology, parallel axes, dtypes,
  optimizer/schedule inputs, jax/jaxlib versions) and *excludes* volatile
  host-side knobs (trace dirs, checkpoint paths, log cadence, fault plans).
  The one program-affecting piece of fault injection — compiled-in NaN-grad
  injection and the bad-step guard — re-enters the hash via the *resolved*
  plan for this restart attempt, so a recovery attempt whose injected fault
  has expired fingerprints identically to a clean run and can reuse its
  executable.
- ``StepExecutableCache`` stores ``jax.experimental.serialize_executable``
  payloads under ``<compile_cache>/aot/<key>.aotx``; a warm restart
  deserializes the executable and skips tracing entirely. Any mismatch
  (format, jax version, unreadable payload) is a silent miss that falls
  back to a cold ``lower().compile()`` — never a failure.

A cache hit loads byte-identical XLA output for the same program, so
numerics are unchanged (the zero1<->replicated and chaos-soak bitwise pins
hold with the cache hot or cold).

The serve engine rides the same ``StepExecutableCache`` under its own
``serve/engine.py serve_fingerprint`` (a full-``ServeConfig`` hash, so
fast-path fields — ``prefix_cache``, ``spec_draft_model``, ``spec_k`` —
extend the key automatically): prefill buckets, decode, and the fast
path's block-prefill / page-clone / draft / verify programs all warm-boot
from it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import sys
import time
from typing import Any, Optional

from distributeddeeplearning_tpu.perf import compile_cache

FORMAT_VERSION = 1

# TrainConfig fields that never reach the compiled step program: paths,
# cadences, watchdog thresholds, and host-side fault orchestration. The
# nan-grad/guard portion of fault handling DOES reach the program and is
# re-added as _fault_program below from the plan resolved for this attempt.
VOLATILE_FIELDS = frozenset({
    "log_every", "eval_every_epochs",
    "checkpoint_dir", "checkpoint_every_steps", "resume",
    "profile_steps", "profile_dir",
    "trace_dir", "trace_steps", "trace_max_events",
    "straggler_threshold", "bad_step_limit",
    "fault_plan", "fail_at_step",
    "compile_cache_dir",
})

# Same for DataConfig: host-pipeline knobs that leave batch shapes alone.
VOLATILE_DATA_FIELDS = frozenset({
    "data_dir", "loader", "shuffle_buffer", "prefetch_depth",
    "loader_timeout_s", "loader_retries",
})


def _versions() -> dict[str, str]:
    import jax
    import jaxlib
    # The RNG lowering is part of the compiled program: an executable built
    # under legacy threefry replays legacy bits forever, so a flag flip
    # (set in the package __init__) must miss the cache, not poison it.
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "threefry_partitionable":
                str(bool(jax.config.jax_threefry_partitionable))}


def config_fingerprint(config, *, total_steps: Optional[int] = None,
                       extra: Any = None) -> str:
    """Stable hash of everything about ``config`` that shapes the compiled
    step program. Equal configs -> equal keys; volatile fields (trace dirs,
    checkpoint paths, host-side fault plans, cadences) never perturb it;
    a jax/jaxlib upgrade always does.

    ``total_steps`` must be passed when known: the LR schedule bakes it
    into the update computation (train/optim.py), so two runs differing
    only in horizon compile different programs.
    """
    d = dataclasses.asdict(config)
    for field in VOLATILE_FIELDS:
        d.pop(field, None)
    if isinstance(d.get("data"), dict):
        for field in VOLATILE_DATA_FIELDS:
            d["data"].pop(field, None)
    # Resolved per-attempt fault program: nan-grad injection steps and the
    # bad-step guard are compiled into the step (train/steps._guard_config).
    from distributeddeeplearning_tpu.robustness import faults
    nan_steps = faults.resolve(config).nan_grad_steps()
    d["_fault_program"] = {
        "nan_steps": sorted(nan_steps),
        "guard": bool(nan_steps) or bool(getattr(config, "bad_step_guard",
                                                 False)),
    }
    d["_total_steps"] = total_steps
    d["_versions"] = _versions()
    if extra is not None:
        d["_extra"] = extra
    blob = json.dumps(d, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def runtime_tag() -> str:
    """Device-topology component of executable keys: an executable compiled
    for one platform/chip/mesh size never deserializes onto another."""
    import jax
    devices = jax.devices()
    dev = devices[0]
    return (f"{dev.platform}:{getattr(dev, 'device_kind', '?')}:"
            f"{len(devices)}x{jax.process_count()}")


def _aval_signature(args) -> list:
    """Tree structure + per-leaf (shape, dtype) of the call arguments."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return [str(treedef),
            [(tuple(getattr(x, "shape", ())),
              str(getattr(x, "dtype", type(x).__name__))) for x in leaves]]


def donation_signature(compiled_exec) -> Optional[str]:
    """The executable's ``input_output_alias`` header from its HLO text —
    the compiled encoding of which inputs were donated. ``None`` when the
    text or header is unavailable (older jax, partial dumps): the caller
    treats that as "cannot check", never as a mismatch."""
    try:
        text = compiled_exec.as_text()
        marker = "input_output_alias="
        start = text.index(marker) + len(marker)
        brace = text.index("{", start)
        depth = 0
        for i in range(brace, min(len(text), brace + 100_000)):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    return "".join(text[brace:i + 1].split())
        return None
    except Exception:  # noqa: BLE001 — absence of evidence, not mismatch
        return None


class StepExecutableCache:
    """Fingerprint-keyed store of serialized step executables.

    One instance per run (train/loop.build creates it); disabled entirely
    when the compile cache is off (``cache_dir=None``). All methods are
    best-effort: a broken entry is a miss, a failed save is a warning.
    """

    def __init__(self, cache_dir: Optional[str], fingerprint: str):
        self.cache_dir = cache_dir
        self.dir = (os.path.join(cache_dir, compile_cache.AOT_SUBDIR)
                    if cache_dir else None)
        self.fingerprint = fingerprint
        self.hits = 0
        self.misses = 0
        self.failures = 0
        self.saves = 0
        self.sources: dict[str, str] = {}  # step name -> aot_hit | compiled

    @classmethod
    def for_config(cls, config, *, total_steps: Optional[int] = None,
                   cache_dir: Optional[str] = None) -> "StepExecutableCache":
        explicit = (cache_dir if cache_dir is not None
                    else getattr(config, "compile_cache_dir", None))
        resolved = compile_cache.resolve_dir(explicit)
        return cls(resolved, config_fingerprint(config,
                                                total_steps=total_steps))

    @property
    def enabled(self) -> bool:
        return self.dir is not None

    def key(self, name: str, args) -> str:
        blob = json.dumps(
            [self.fingerprint, name, runtime_tag(), _aval_signature(args)],
            sort_keys=True, default=repr)
        return hashlib.sha256(blob.encode()).hexdigest()[:32]

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.aotx")

    def load(self, name: str, key: str):
        """Deserialize the cached executable for ``key``; None on miss or
        on ANY mismatch (format, jax version, corrupt payload) — the caller
        cold-compiles and overwrites the entry."""
        if self.dir is None:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            self.misses += 1
            self.sources[name] = "compiled"
            return None
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
            if payload.get("format") != FORMAT_VERSION:
                raise ValueError(f"format {payload.get('format')!r}")
            if payload.get("versions") != _versions():
                raise ValueError(
                    f"built under jax {payload.get('versions')}, "
                    f"running {_versions()}")
            from jax.experimental import serialize_executable
            fn = serialize_executable.deserialize_and_load(
                payload["executable"], payload["in_tree"],
                payload["out_tree"])
            # Donation backstop (the PR 5 bug class, cheap runtime form of
            # analysis/donation.py): the deserialized executable must
            # donate exactly the inputs it donated when saved. A drifted
            # donation set means a dispatch through this hit could donate
            # buffers the caller still aliases — delete + recompile cold.
            saved_donation = payload.get("donation")
            live_donation = donation_signature(fn)
            if (saved_donation is not None and live_donation is not None
                    and saved_donation != live_donation):
                raise ValueError(
                    f"donation set drifted: saved "
                    f"input_output_alias {saved_donation} != deserialized "
                    f"{live_donation}")
        except Exception as exc:  # noqa: BLE001 - any mismatch = cold path
            self.failures += 1
            self.misses += 1
            self.sources[name] = "compiled"
            print(f"[aot] cached executable for {name} unusable "
                  f"({type(exc).__name__}: {exc}); recompiling cold",
                  file=sys.stderr)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        self.hits += 1
        self.sources[name] = "aot_hit"
        return fn

    def save(self, name: str, key: str, compiled_exec) -> bool:
        """Serialize ``compiled_exec`` under ``key`` (atomic write; every
        process writes identical bytes, last rename wins)."""
        if self.dir is None:
            return False
        try:
            from jax.experimental import serialize_executable
            executable, in_tree, out_tree = serialize_executable.serialize(
                compiled_exec)
            blob = pickle.dumps({
                "format": FORMAT_VERSION,
                "versions": _versions(),
                "runtime": runtime_tag(),
                "name": name,
                "fingerprint": self.fingerprint,
                "executable": executable,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "donation": donation_signature(compiled_exec),
                "saved_at": time.time(),
            })
            os.makedirs(self.dir, exist_ok=True)
            path = self._path(key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        except Exception as exc:  # noqa: BLE001 - saving is optional
            print(f"[aot] could not serialize {name} "
                  f"({type(exc).__name__}: {exc}); run continues uncached",
                  file=sys.stderr)
            return False
        self.saves += 1
        return True

    def stats(self) -> dict[str, Any]:
        return {"aot_hits": self.hits, "aot_misses": self.misses,
                "aot_failures": self.failures, "aot_saves": self.saves,
                "fingerprint": self.fingerprint,
                "sources": dict(self.sources)}

    def flush_stats(self) -> None:
        """Persist counters next to the cache for tools/doctor.py."""
        compile_cache.write_stats(self.cache_dir, self.stats())
