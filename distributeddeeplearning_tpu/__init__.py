"""TPU-native distributed deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
``microsoft/DistributedDeepLearning`` (Horovod+NCCL multi-GPU training of
ImageNet CNNs and BERT), built TPU-first:

- data parallelism via ``shard_map`` + ``psum`` over an ICI device mesh
  (replacing ``hvd.DistributedOptimizer`` / NCCL ring-allreduce);
- tensor / sequence parallelism via ``jit`` + ``NamedSharding`` rules
  (XLA emits the collectives — there is no userland ring);
- input pipelines with device-side prefetch (replacing CUDA/DALI loaders);
- a pod-slice launcher (replacing mpirun / Batch-AI job submission).

Reference provenance: the reference checkout at /root/reference was empty at
build time (see SURVEY.md header); the capability contract is BASELINE.json
(north star + 5 acceptance configs), cited throughout as BASELINE.json:N.
"""

__version__ = "0.1.0"

import jax as _jax

# Sharding-invariant RNG, set once for every entry point (train.py, bench.py,
# launch.py children, tests). The legacy threefry lowering lets the SPMD
# partitioner re-derive per-shard bits, so the *same* (seed, step) batch —
# and the same init draw — comes out different under a different mesh. That
# silently breaks the elastic contract: a re-formed attempt that shrinks the
# data axis (launch.py --elastic-geometry) would train on different synthetic
# batches than the geometry it resumed from, and cross-geometry trajectory
# parity (tests/test_elastic_resume.py) is off by per-step data noise, not
# ULPs. Partitionable threefry makes every draw a pure function of
# (key, position) regardless of layout. Flipping this changes the bit-stream,
# so it is part of the AOT cache fingerprint (perf/aot.py).
_jax.config.update("jax_threefry_partitionable", True)

from distributeddeeplearning_tpu.config import (  # noqa: F401
    DataConfig,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
)
