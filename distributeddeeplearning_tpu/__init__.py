"""TPU-native distributed deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
``microsoft/DistributedDeepLearning`` (Horovod+NCCL multi-GPU training of
ImageNet CNNs and BERT), built TPU-first:

- data parallelism via ``shard_map`` + ``psum`` over an ICI device mesh
  (replacing ``hvd.DistributedOptimizer`` / NCCL ring-allreduce);
- tensor / sequence parallelism via ``jit`` + ``NamedSharding`` rules
  (XLA emits the collectives — there is no userland ring);
- input pipelines with device-side prefetch (replacing CUDA/DALI loaders);
- a pod-slice launcher (replacing mpirun / Batch-AI job submission).

Reference provenance: the reference checkout at /root/reference was empty at
build time (see SURVEY.md header); the capability contract is BASELINE.json
(north star + 5 acceptance configs), cited throughout as BASELINE.json:N.
"""

__version__ = "0.1.0"

from distributeddeeplearning_tpu.config import (  # noqa: F401
    DataConfig,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
)
