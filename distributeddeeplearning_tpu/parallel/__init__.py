"""Parallelism: device meshes, sharding rules, and collective-backed train
steps. This package is the TPU-native replacement for the reference's entire
communication stack (Horovod C++ core + NCCL + MPI — SURVEY.md §2 #7-#9):
collectives are emitted by XLA from ``shard_map``/``jit`` sharding
annotations and ride ICI/DCN; rendezvous is ``jax.distributed``.
"""

from distributeddeeplearning_tpu.parallel.collectives import (  # noqa: F401
    BucketPlan,
    all_reduce,
    all_reduce_gradients,
    plan_buckets,
)
from distributeddeeplearning_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    make_mesh,
)
from distributeddeeplearning_tpu.parallel.sharding import (  # noqa: F401
    logical_rules,
    mesh_sharding,
)
