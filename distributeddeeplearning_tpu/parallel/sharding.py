"""Logical-axis → mesh-axis sharding rules (GSPMD path).

Models annotate kernels with *logical* names (see models/bert.py); this module
maps them onto the mesh so ``jit`` + ``NamedSharding`` lets XLA insert the
collectives. This replaces hand-written NCCL calls entirely — the Megatron-style
tensor-parallel patterns (column-shard QKV/MLP-in, row-shard out-projections,
vocab-parallel embedding) fall out of three rules on ``model``.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu.config import ParallelConfig


def logical_rules(parallel: ParallelConfig) -> tuple[tuple[str, Any], ...]:
    """Rules consumed by ``nn.logical_to_mesh_sharding``.

    - ``batch`` → ("data", "fsdp"): the DP axes (BASELINE.json:5).
    - ``seq`` → "seq": sequence/context parallelism over activations.
    - ``heads``/``mlp``/``vocab`` → "model": Megatron-style TP.
    - ``embed`` → "fsdp": parameter sharding when fsdp>1, else replicated.
      (On the explicit-DP path, ``--optimizer-sharding zero3`` subsumes this
      rule: params live 1/N-chunked in the ZeRO layout and are all-gathered
      per fusion bucket, so fsdp>1 alone no longer forces GSPMD — see
      ``loop.uses_gspmd``.)
    - ``experts`` → "expert": MoE expert parallelism (models/moe.py) — the
      dispatch/combine einsums become XLA all-to-alls over ICI.
    - ``layers`` → "pipeline": stage-stacked layer params (parallel/pipeline.py).
    """
    rules = [
        ("batch", ("data", "fsdp")),
        ("seq", "seq"),
        ("heads", "model"),
        ("mlp", "model"),
        ("vocab", "model"),
        ("embed", "fsdp" if parallel.fsdp > 1 else None),
        ("embed_out", None),
        ("experts", "expert"),
        ("layers", "pipeline"),
    ]
    return tuple(rules)


def mesh_sharding(tree: Any, mesh: Mesh,
                  parallel: ParallelConfig) -> Any:
    """NamedShardings for a pytree carrying flax Partitioned metadata.

    Leaves without metadata (e.g. biases, LayerNorm scales created without
    ``with_logical_partitioning``) replicate.
    """
    specs = nn.get_partition_spec(tree)
    return nn.logical_to_mesh_sharding(specs, mesh, list(logical_rules(parallel)))


def batch_sharding(mesh: Mesh, *, seq_dim: Optional[int] = None) -> NamedSharding:
    """Input-batch sharding: dim0 over the DP axes, optionally a sequence dim
    over ``seq`` (sp for token inputs)."""
    spec = [("data", "fsdp")]
    if seq_dim is not None:
        spec += [None] * (seq_dim - 1) + ["seq"]
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_replicated(tree: Any, mesh: Mesh) -> Any:
    """device_put a host pytree fully replicated over the mesh."""
    return jax.device_put(tree, replicated(mesh))
