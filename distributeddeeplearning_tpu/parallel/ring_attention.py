"""Ring attention — blockwise sequence/context parallelism over the ``seq``
mesh axis.

Long-context scaling the TPU-native way: each device holds one sequence shard
of Q, K, V; K/V blocks rotate around the ``seq`` axis ring with
``lax.ppermute`` (one ICI-neighbour hop per step) while each device
accumulates its queries' attention with an online-softmax running state
(max ``m``, normalizer ``l``, weighted-value ``acc`` — the flash-attention
recurrence). After ``seq`` steps every query has seen every key, yet no
device ever materializes the full (S, S) score matrix or the full K/V — HBM
stays O(S_local) and the permutes overlap with block compute under XLA's
scheduler.

The reference had no long-context machinery at all (SURVEY.md §5.7 — a
CNN-era DP tutorial); this subsystem is the capability the port adds to make
sequence models first-class on TPU. Used inside the GSPMD train step via a
nested ``shard_map`` (models/bert.py) so K/V rotation rides ICI explicitly
while XLA still lays out everything else.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu import compat
from distributeddeeplearning_tpu.ops.masks import block_causal_mask

# Large-negative instead of -inf: keeps exp() exactly 0 without inf-inf NaN
# hazards in the running-max recurrence.
_NEG = -1e30


def _block_update(q, k, v, kv_mask, m, l, acc, scale, tri=None, drop=None):
    """One online-softmax accumulation step against a K/V block.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); kv_mask: (B, Sk) True=attend.
    ``tri``: optional (Sq, Sk) bool causal mask for this block pair.
    Running state m, l: (B, H, Sq); acc: (B, H, Sq, D), all float32.

    ``drop``: optional attention-probability dropout as
    (rate, seed, b0, h0, h_total, q0, k0) — rate static, the rest traced
    scalars placing this block in GLOBAL (batch·head, query, key)
    coordinates. The mask is the counter-based hash of those coordinates
    (ops/hash_dropout.py), so every ring step, every shard, and every other
    attention impl realizes the identical mask for the same seed. ``l``
    accumulates undropped p (dense semantics: normalize, then drop);
    backward is plain autodiff through this function, hence consistent.
    """
    keep = jnp.broadcast_to(kv_mask[:, None, None, :],
                            (q.shape[0], 1, q.shape[1], k.shape[1]))
    if tri is not None:
        keep = keep & tri[None, None]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(keep, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Re-mask after exp: a fully-masked block would otherwise contribute
    # exp(_NEG - _NEG) = 1 per key.
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(keep, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    if drop is not None and drop[0] > 0.0:
        from distributeddeeplearning_tpu.ops.hash_dropout import keep_mask

        rate, seed, b0, h0, h_tot, q0, k0 = drop
        nb, sq, nh = q.shape[0], q.shape[1], q.shape[2]
        sk = k.shape[1]
        bh = ((b0 + jnp.arange(nb))[:, None] * h_tot
              + h0 + jnp.arange(nh)[None, :])                # (B, H)
        rows = q0 + jnp.arange(sq)
        cols = k0 + jnp.arange(sk)
        km = keep_mask(seed, bh[:, :, None, None],
                       rows[None, None, :, None],
                       cols[None, None, None, :], rate)
        p = jnp.where(km, p * (1.0 / (1.0 - rate)), 0.0)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, kv_mask, *, axis_name: str = "seq",
                   causal: bool = False, dropout=None):
    """Exact attention over a ring of sequence shards (optionally causal).

    Call under ``shard_map`` with the sequence dim sharded on ``axis_name``.
    Shapes (per shard): q/k/v (B, S_local, H, D); kv_mask (B, S_local) bool.
    Returns (B, S_local, H, D) in q.dtype. Collapses to one local block (no
    permutes) when the axis has size 1, so the same code path serves
    single-chip runs.

    ``causal=True`` masks by *global* sequence position: ring step r brings
    shard ``(i - r) mod n``'s K/V to shard i, so each block pair gets the
    (Sq, Sk) triangle of ``kv_pos <= q_pos`` — full for past blocks, the
    diagonal triangle for the local block, empty for future blocks. A
    future block's arrival skips ``_block_update`` entirely via ``lax.cond``
    (its contribution is exactly zero), reclaiming the ~2x FLOP overhead
    the uniform schedule would pay; the ppermutes still run every step, so
    the ring schedule — and hence the collective pattern XLA compiles —
    stays identical on every device (VERDICT r2 Weak #3).
    """
    b, sq, h, d = q.shape
    scale = d ** -0.5
    n = compat.axis_size(axis_name)
    m = jnp.full((b, h, sq), _NEG, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    kv_mask = kv_mask.astype(jnp.bool_)
    idx = (lax.axis_index(axis_name) if causal or dropout is not None
           else None)

    def blk_drop(src):
        # Contiguous sharding: shard i holds natural positions
        # [i*sq, (i+1)*sq) — the dropout hash coordinates stay global.
        if dropout is None:
            return None
        return (*dropout, idx * sq, src * sq)

    # Local block first, outside the loop: it both seeds the carry with the
    # right varying-axes type (the NEG/zero inits are unvarying constants,
    # which shard_map's loop typing rejects as a carry) and leaves exactly
    # n-1 permutes in the ring.
    tri = block_causal_mask(idx, idx, sq, sq) if causal else None
    m, l, acc = _block_update(q, k, v, kv_mask, m, l, acc, scale, tri,
                              blk_drop(idx))
    if n > 1:
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(r, carry):
            m, l, acc, k, v, msk = carry
            # Rotate K/V (and their padding mask) one ICI neighbour along
            # the ring, then fold the arriving block into the running state.
            k, v, msk = lax.ppermute((k, v, msk), axis_name, perm)
            src = (idx - r) % n if idx is not None else None
            if causal:

                def fold(state):
                    tri = block_causal_mask(idx, src, sq, sq)
                    return _block_update(q, k, v, msk, *state, scale, tri,
                                         blk_drop(src))

                # src > idx means every arriving key is in this shard's
                # future: the whole block is masked and contributes nothing.
                # lax.cond keeps it off the execution path (the predicate is
                # a local scalar, so each device branches independently
                # while the ppermute above stays uniform across the ring).
                m, l, acc = lax.cond(src > idx,
                                     lambda state: state, fold, (m, l, acc))
            else:
                m, l, acc = _block_update(q, k, v, msk, m, l, acc, scale,
                                          None, blk_drop(src))
            return m, l, acc, k, v, msk

        m, l, acc, *_ = lax.fori_loop(
            1, n, body, (m, l, acc, k, v, kv_mask))

    out = acc / jnp.maximum(l, 1e-30)[..., None]          # (B, H, Sq, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)       # (B, Sq, H, D)


def ring_attention_sharded(q, k, v, kv_mask, *,
                           mesh: Optional[jax.sharding.Mesh] = None,
                           seq_axis: str = "seq",
                           batch_axes=("data", "fsdp"),
                           head_axis: str = "model",
                           causal: bool = False,
                           zigzag: bool = False,
                           dropout_rate: float = 0.0, dropout_seed=None):
    """GSPMD-embeddable wrapper: shard_map over (batch, seq, heads).

    Takes *global* (B, S, H, D) arrays inside a jit-traced program (ambient
    mesh from ``use_mesh``), pins the ring layout — batch over the DP axes,
    sequence over ``seq``, heads over ``model`` — and runs ``ring_attention``
    per shard. Heads stay independent, so head sharding composes freely with
    the sequence ring. ``zigzag=True`` (implies causal) maps
    :func:`zigzag_ring_attention` instead — inputs/outputs must already be
    in zigzag layout (:func:`zigzag_indices`).

    ``dropout_rate`` > 0: attention-probability dropout via the global
    counter-based hash mask (ops/hash_dropout.py) — each shard offsets its
    coordinates by its mesh position, so the realized mask equals the dense
    impl's at any dp x tp x sp sharding.
    """
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("ring_attention_sharded: dropout_rate > 0 needs "
                         "a dropout_seed")
    if mesh is None:
        ambient = compat.get_abstract_mesh()
        if ambient is None or ambient.empty:
            # No mesh context (single-device apply / notebook use): one local
            # block is the whole ring. Zigzag over one shard with identity
            # permutation is plain causal attention.
            drop = ((float(dropout_rate), dropout_seed, 0, 0, q.shape[2])
                    if dropout_rate > 0.0 else None)
            return _local_attention(q, k, v, kv_mask,
                                    causal=causal or zigzag, dropout=drop)
        mesh_shape = ambient.shape
    else:
        mesh_shape = mesh.shape
    if zigzag and mesh_shape.get(seq_axis, 1) <= 1:
        # One seq shard: the zigzag permutation is the identity and its
        # chunk split would demand an even length for nothing — the plain
        # causal ring (a single local block) is the same computation.
        zigzag, causal = False, True
    qkv_spec = P(batch_axes, seq_axis, head_axis, None)
    mask_spec = P(batch_axes, seq_axis)
    seed_arr = jnp.reshape(
        jnp.asarray(dropout_seed if dropout_seed is not None else 0,
                    jnp.int32), (1,))

    def fn(qs, ks, vs, ms, seed1):
        drop = None
        if dropout_rate > 0.0:
            from distributeddeeplearning_tpu.ops.hash_dropout import (
                shard_bh_offsets)

            b0, h0, h_tot = shard_bh_offsets(batch_axes, head_axis,
                                             qs.shape[0], qs.shape[2])
            drop = (float(dropout_rate), seed1[0], b0, h0, h_tot)
        if zigzag:
            return zigzag_ring_attention(qs, ks, vs, ms,
                                         axis_name=seq_axis, dropout=drop)
        return ring_attention(qs, ks, vs, ms, axis_name=seq_axis,
                              causal=causal, dropout=drop)

    mapped = compat.shard_map(
        fn, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec, P(None)),
        out_specs=qkv_spec)
    return mapped(q, k, v, kv_mask, seed_arr)


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) causal ring — the latency fix the plain causal
# ring cannot deliver (BASELINE.md r3 note): with contiguous sharding the
# last shard computes every block, so the lockstep ring's critical path is
# unchanged by skipping work elsewhere. Zigzag sharding gives shard i the
# chunk PAIR (i, 2n-1-i) of 2n global chunks — one early (light) and one
# late (heavy) — which makes every shard's causal work equal by
# construction: per ring arrival, each shard folds exactly two chunk-pair
# updates (three on the local step), so the critical path drops from n
# full-block updates to ~n single-chunk pairs (~2x at equal total FLOPs).
# ---------------------------------------------------------------------------

def zigzag_indices(seq_len: int, n_shards: int):
    """Permutation taking the natural sequence to zigzag-shard order.

    ``x[:, perm]`` lays the sequence out so an even split over ``n_shards``
    gives shard i the chunks (i, 2n-1-i); ``inv`` undoes it
    (``y[:, inv]`` returns to natural order).
    """
    import numpy as np

    assert seq_len % (2 * n_shards) == 0, (seq_len, n_shards)
    c = seq_len // (2 * n_shards)
    chunks = np.arange(seq_len).reshape(2 * n_shards, c)
    perm = np.concatenate([
        np.concatenate([chunks[i], chunks[2 * n_shards - 1 - i]])
        for i in range(n_shards)])
    inv = np.argsort(perm)
    return perm, inv


def _zigzag_pairs(i: int, src: int, n: int):
    """Pure-python mirror of the traced schedule: the (q_chunk, kv_chunk)
    pairs shard ``i`` computes when shard ``src``'s K/V arrives. The
    schedule-balance test sums this statically; the traced code below uses
    the same predicates."""
    qlo, qhi = i, 2 * n - 1 - i
    klo, khi = src, 2 * n - 1 - src
    pairs = []
    if klo <= qlo:
        pairs.append((qlo, klo))
    if khi <= qlo:  # provably never (khi >= n > qlo); kept for the mirror
        pairs.append((qlo, khi))
    if klo <= qhi:  # provably always (klo < n <= qhi)
        pairs.append((qhi, klo))
    if khi <= qhi:
        pairs.append((qhi, khi))
    return pairs


def zigzag_ring_attention(q, k, v, kv_mask, *, axis_name: str = "seq",
                          dropout=None):
    """Causal ring attention over zigzag-sharded sequences.

    Call under ``shard_map`` with inputs already in zigzag layout
    (:func:`zigzag_indices`): per shard, the local (B, S_local, H, D)
    arrays are ``concat(chunk_i, chunk_{2n-1-i})``. Output is in the same
    local layout (undo globally with ``inv``). Numerics are exactly causal
    attention in natural order (tests assert vs the dense reference).
    """
    b, sl, h, d = q.shape
    c = sl // 2
    scale = d ** -0.5
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    kv_mask = kv_mask.astype(jnp.bool_)

    def halves(x):
        return x[:, :c], x[:, c:]

    def init():
        return (jnp.full((b, h, c), _NEG, jnp.float32),
                jnp.zeros((b, h, c), jnp.float32),
                jnp.zeros((b, h, c, d), jnp.float32))

    qlo, qhi = halves(q)
    qlo_c, qhi_c = idx, 2 * n - 1 - idx  # global chunk indices

    def fold(state, qh, qc, kh, kc, msk, tri: bool):
        mask = block_causal_mask(qc, kc, c, c) if tri else None
        # Zigzag chunk qc holds NATURAL positions [qc*c, (qc+1)*c): keying
        # the dropout hash by them makes the permuted-layout mask equal the
        # dense impl's natural-order mask element for element.
        drop = (*dropout, qc * c, kc * c) if dropout is not None else None
        return _block_update(qh, kh[0], kh[1], msk, *state, scale, mask,
                             drop)

    # Local arrival (src == idx): seeds the carries with varying-type values
    # (see the non-zigzag ring above) and leaves n-1 permutes in the ring.
    klo, khi = halves(k)
    vlo, vhi = halves(v)
    mlo, mhi = halves(kv_mask)
    lo = fold(init(), qlo, qlo_c, (klo, vlo), qlo_c, mlo, tri=True)
    hi = fold(init(), qhi, qhi_c, (klo, vlo), qlo_c, mlo, tri=False)
    hi = fold(hi, qhi, qhi_c, (khi, vhi), qhi_c, mhi, tri=True)

    if n > 1:
        perm = [(j, (j + 1) % n) for j in range(n)]

        def body(r, carry):
            lo, hi, k, v, msk = carry
            k, v, msk = lax.ppermute((k, v, msk), axis_name, perm)
            src = (idx - r) % n
            klo, khi = halves(k)
            vlo, vhi = halves(v)
            mlo, mhi = halves(msk)
            # Arriving chunk pair (src, 2n-1-src); every computed pair is a
            # FULL block (strict chunk inequality — the only triangles are
            # the local ones above), so tri=False throughout. The two conds
            # mirror _zigzag_pairs: each shard folds exactly two of the
            # three candidate pairs per arrival — balanced by construction.
            lo = lax.cond(
                src < idx,
                lambda s: fold(s, qlo, qlo_c, (klo, vlo), src, mlo,
                               tri=False),
                lambda s: s, lo)
            hi = fold(hi, qhi, qhi_c, (klo, vlo), src, mlo, tri=False)
            hi = lax.cond(
                src > idx,
                lambda s: fold(s, qhi, qhi_c, (khi, vhi), 2 * n - 1 - src,
                               mhi, tri=False),
                lambda s: s, hi)
            return lo, hi, k, v, msk

        lo, hi, *_ = lax.fori_loop(1, n, body, (lo, hi, k, v, kv_mask))

    def finish(state):
        m, l, acc = state
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 2, 1, 3)

    return jnp.concatenate([finish(lo), finish(hi)], axis=1).astype(q.dtype)


def zigzag_ring_attention_sharded(q, k, v, kv_mask, **kw):
    """GSPMD-embeddable wrapper for :func:`zigzag_ring_attention` — same
    contract as :func:`ring_attention_sharded`, inputs/outputs in zigzag
    layout."""
    return ring_attention_sharded(q, k, v, kv_mask, causal=True,
                                  zigzag=True, **kw)


def _local_attention(q, k, v, kv_mask, *, causal: bool = False,
                     dropout=None):
    """The ring's single-block case without a mesh: one _block_update pass
    (still exact, still O(S) memory in scores per block — here S is global)."""
    b, sq, h, d = q.shape
    m = jnp.full((b, h, sq), _NEG, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    tri = block_causal_mask(0, 0, sq, sq) if causal else None
    drop = (*dropout, 0, 0) if dropout is not None else None
    m, l, acc = _block_update(q, k, v, kv_mask.astype(jnp.bool_), m, l, acc,
                              d ** -0.5, tri, drop)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
