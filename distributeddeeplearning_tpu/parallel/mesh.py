"""Device-mesh construction.

The mesh is the TPU-native replacement for Horovod's rank/size world
(SURVEY.md §2 #7-#9): axis ``data`` is the gradient-allreduce axis
(BASELINE.json:5 "psum over ICI"); ``fsdp`` shards parameters along the same
data-parallel family; ``model``/``seq``/``expert``/``pipeline`` host tensor,
sequence, expert, and pipeline parallelism. Size-1 axes are free, so every
program is written against the full six-axis mesh and collapses cleanly to
single-chip.

Axis order puts ``model``/``seq`` innermost so tensor/sequence collectives
(all-gather, ppermute rings) land on the fastest ICI neighbours, while pure-DP
psums tolerate the outer (slower, possibly DCN) dimensions — the standard
TPU mesh layout recipe.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from distributeddeeplearning_tpu.config import ParallelConfig

MESH_AXES: tuple[str, ...] = (
    "pipeline", "data", "fsdp", "expert", "seq", "model")


def make_mesh(parallel: ParallelConfig,
              devices: Optional[Sequence[jax.Device]] = None,
              backend: Optional[str] = None) -> Mesh:
    """Build a Mesh matching ``parallel``'s axis sizes.

    ``backend="cpu"`` forces the mesh onto the host's CPU devices even when
    an accelerator platform is active — the library-level counterpart of
    ``train.py --backend=cpu`` (BASELINE.json:5), so
    ``TrainConfig(backend="cpu")`` works from Python too. ``backend="tpu"``
    (the default) uses the ambient platform's devices, matching the CLI's
    env-var dispatch.

    Uses ``mesh_utils.create_device_mesh`` on real TPU platforms so the mesh
    axes align with the physical ICI torus; falls back to a reshape for CPU
    test devices (where topology is fake anyway).
    """
    if devices is None:
        devices = jax.devices("cpu") if backend == "cpu" else jax.devices()
    sizes = parallel.axis_sizes()
    shape = tuple(sizes[a] for a in MESH_AXES)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh axes {dict(zip(MESH_AXES, shape))} need {n} devices, "
            f"have {len(devices)}")
    devices = list(devices)[:n]  # sub-mesh on the first n devices
    if devices[0].platform == "tpu":
        num_slices = len({getattr(d, "slice_index", 0) for d in devices})
        if num_slices > 1:
            # Multi-slice pod: slices are joined by DCN (the InfiniBand role —
            # SURVEY.md §5.8), so the gradient-allreduce axes must span
            # slices while tensor/sequence collectives stay on intra-slice
            # ICI. create_hybrid_device_mesh lays devices out exactly so.
            per_slice, dcn = _hybrid_shapes(shape, num_slices)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=list(devices))
        else:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=list(devices))
    elif parallel.emulate_slices > 1:
        # Emulated multi-slice layout (validation): treat device blocks of
        # size n/num_slices as slices and arrange each global axis
        # DCN-major / per-slice-minor — the same arrangement
        # create_hybrid_device_mesh produces on a real pod, so the sharding
        # rules and collectives compile against the hybrid layout without
        # multi-slice hardware.
        per_slice, dcn = _hybrid_shapes(shape, parallel.emulate_slices)
        k = len(shape)
        arr = np.asarray(list(devices)).reshape(tuple(dcn) + tuple(per_slice))
        perm = [x for i in range(k) for x in (i, k + i)]
        dev_array = arr.transpose(perm).reshape(shape)
    else:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def _hybrid_shapes(shape: tuple[int, ...],
                   num_slices: int) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split a global mesh shape into (per-slice ICI shape, DCN shape).

    DCN (slow, inter-slice) carries the outermost axes in MESH_AXES order —
    ``pipeline`` first, then ``data`` — because pipeline stage boundaries and
    gradient allreduces tolerate DCN latency, while ``model``/``seq``
    collectives are per-layer and must stay on ICI. Each consumed axis size
    must be divisible by its DCN share.
    """
    per_slice, dcn = list(shape), [1] * len(shape)
    remaining = num_slices
    for i, axis in enumerate(MESH_AXES):
        if remaining == 1:
            break
        if axis not in ("pipeline", "data"):
            continue
        take = np.gcd(per_slice[i], remaining)
        if take > 1:
            dcn[i] = int(take)
            per_slice[i] //= int(take)
            remaining //= int(take)
    if remaining != 1:
        raise ValueError(
            f"cannot distribute {num_slices} slices over the "
            f"pipeline/data axes of mesh {dict(zip(MESH_AXES, shape))}; "
            f"make pipeline*data divisible by the slice count")
    return tuple(per_slice), tuple(dcn)


def data_axis_names(parallel: ParallelConfig) -> tuple[str, ...]:
    """Mesh axes over which the global batch is split (and grads psummed)."""
    del parallel  # size-1 axes are no-ops, so both are always safe to name
    return ("data", "fsdp")


def data_parallel_degree(parallel: ParallelConfig) -> int:
    """Number of data shards (product of the data-parallel family axes).

    This is the degree the elastic launcher re-plans on host loss/gain
    (launch.py --elastic): gradients are allreduce-MEANS over the data axes
    at a fixed global batch, so the degree can change between attempts while
    the optimizer trajectory stays bitwise (docs/fault_tolerance.md).
    """
    return int(parallel.data) * int(parallel.fsdp)


def use_mesh(mesh: Mesh):
    """Ambient-mesh context manager, across jax API renames.

    Needed so ``with_sharding_constraint``/flax logical constraints can
    resolve bare PartitionSpecs during tracing. Newest name first; on JAX
    generations predating both ``use_mesh`` and ``set_mesh`` the Mesh object
    itself is the context manager that installs the thread-resources env.
    """
    setter = (getattr(jax.sharding, "use_mesh", None)
              or getattr(jax.sharding, "set_mesh", None))
    return setter(mesh) if setter is not None else mesh


def local_mesh_description(mesh: Mesh) -> str:
    return ", ".join(f"{a}={s}" for a, s in mesh.shape.items() if s > 1) or "1 device"
