"""ZeRO-1 sharded optimizer layout for the explicit-DP path.

The bucketed ring all-reduce (parallel/collectives.py) already materializes
the ZeRO-1 partition as its intermediate: after ``psum_scatter`` each shard
holds the reduced 1/N chunk of every bucket, and the trailing ``all_gather``
throws that structure away so every shard can run the SAME full optimizer
update. ZeRO-1 (ZeRO stage 1, Rajbhandari et al.) keeps it instead: the
optimizer update runs on each shard's chunk only, optimizer state lives
permanently 1/N-sharded, and the ``all_gather`` moves the *updated
parameters* rather than the summed gradients — identical communication
volume (one reduce-scatter + one all-gather of the parameter bytes per
step), optimizer HBM and update FLOPs divided by the DP degree.

Layout: per-leaf chunking that PRESERVES the parameter treedef. Every leaf
is raveled, zero-padded to a multiple of the axis size N, and split into N
contiguous chunks; shard k owns elements ``[k*c, (k+1)*c)`` of every leaf.
Keeping one chunk per leaf (instead of slicing the concatenated bucket)
means the chunk tree has the same structure and relative magnitudes as the
parameter tree, so path-keyed weight-decay masks apply unchanged and
per-layer trust-ratio norms (LARS/LAMB) need only a cross-shard ``psum`` of
squared sums (train/optim.py) to be exact. Bucket fusion is kept at the
collective level: each fusion bucket's member leaves are packed into ONE
``(N, row)`` payload — row k carrying every member's chunk k — so one
``psum_scatter``/``all_gather`` launches per bucket, exactly like the fused
all-reduce.

Padding is benign through every supported optimizer: padded gradient
elements are zero on all shards, so momentum/Adam moments stay zero, the
update there is zero, and squared-sum norms gain nothing.

Checkpoint compatibility (train/checkpoint.py): :class:`Zero1StateConverter`
gathers the chunked optimizer state into the CANONICAL layout — each leaf
restored to its parameter's shape, padding stripped — before save, and pads
and re-shards on restore. The canonical layout is byte-identical to what the
replicated path saves, so zero1 checkpoints restore replicated, replicated
checkpoints restore into zero1, and the DP degree may change between save
and resume (the pad is a function of N and is never persisted).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu.observability import telemetry
from distributeddeeplearning_tpu.parallel.collectives import (
    _MB, AxisNames, BucketPlan, DEFAULT_BUCKET_MB, _numel, plan_buckets)


@dataclasses.dataclass(frozen=True)
class Zero1Layout:
    """Chunk assignment for ONE parameter tree shape on ONE axis size.

    ``chunk_sizes[i]`` is the per-shard chunk length of flatten-order leaf
    i: ``ceil(numel_i / axis_size)``; the leaf's padded flat length is
    ``chunk_sizes[i] * axis_size``. Bucket membership reuses the
    deterministic path-keyed planner, so the payload layout is stable under
    dict insertion-order churn exactly like the fused all-reduce.
    """

    plan: BucketPlan
    axis_size: int
    chunk_sizes: tuple[int, ...]

    @property
    def num_leaves(self) -> int:
        return self.plan.num_leaves

    def padded_size(self, i: int) -> int:
        return self.chunk_sizes[i] * self.axis_size

    def describe(self) -> str:
        total = sum(_numel(s) for s in self.plan.shapes)
        padded = sum(self.padded_size(i) for i in range(self.num_leaves))
        return (f"1/{self.axis_size} per shard over "
                f"{len(self.plan.buckets)} bucket(s), "
                f"{self.num_leaves} leaves, pad {padded - total} elems")


def build_layout(tree, axis_size: int,
                 bucket_bytes: Optional[int] = None) -> Zero1Layout:
    """Plan the ZeRO-1 chunk layout for ``tree`` (arrays or shape structs —
    shapes are static, so this works on tracers at trace time)."""
    if axis_size < 1:
        raise ValueError(f"axis_size must be >= 1 (got {axis_size})")
    plan = plan_buckets(tree, bucket_bytes)
    chunk_sizes = tuple(-(-_numel(s) // axis_size) for s in plan.shapes)
    return Zero1Layout(plan=plan, axis_size=axis_size,
                       chunk_sizes=chunk_sizes)


def layout_from_options(tree, axis_size: int, options=None
                        ) -> tuple[Zero1Layout, Optional[Any]]:
    """(layout, scatter payload dtype) per the run's AllReduceConfig —
    the same bucket-size/dtype policy knobs the fused all-reduce reads.
    The payload dtype applies to the gradient reduce-scatter only; the
    parameter all-gather always moves the parameters' own dtype."""
    bucket_mb = getattr(options, "bucket_mb", DEFAULT_BUCKET_MB)
    dtype_name = getattr(options, "dtype", "float32") or "float32"
    if dtype_name not in ("float32", "bfloat16"):
        raise ValueError(
            f"allreduce dtype {dtype_name!r} not supported; use 'float32' "
            f"(reduce in the gradients' own dtype) or 'bfloat16' "
            f"(compressed payload, fp32 master restored after the reduce)")
    payload = jnp.bfloat16 if dtype_name == "bfloat16" else None
    return build_layout(tree, axis_size,
                        int(float(bucket_mb) * _MB)), payload


def _check_leaves(layout: Zero1Layout, n: int) -> None:
    if n != layout.num_leaves:
        raise ValueError(f"layout was built for {layout.num_leaves} leaves, "
                         f"tree has {n}")


def _pad_flat(leaf, padded: int):
    flat = leaf.ravel()
    pad = padded - flat.size
    return jnp.pad(flat, (0, pad)) if pad else flat


# ---------------------------------------------------------------------------
# Global (full-array) layout conversions — used for optimizer-state init and
# checkpoint reshard, OUTSIDE shard_map. The chunked global form of a leaf is
# its zero-padded ravel of length chunk*N; placed with P(data, fsdp) on dim 0
# it is exactly the concatenation of the shards' chunks.
# ---------------------------------------------------------------------------

def to_chunked(tree, layout: Zero1Layout):
    """Each leaf -> its padded flat ``(chunk * N,)`` global form."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = [_pad_flat(leaf, layout.padded_size(i))
           for i, leaf in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def from_chunked(tree, layout: Zero1Layout):
    """Inverse of :func:`to_chunked`: strip padding, restore leaf shapes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = []
    for i, leaf in enumerate(leaves):
        shape = layout.plan.shapes[i]
        out.append(leaf[:_numel(shape)].reshape(shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def chunked_struct(tree, layout: Zero1Layout):
    """ShapeDtypeStruct tree of the chunked global form (for eval_shape)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = [jax.ShapeDtypeStruct((layout.padded_size(i),),
                                jnp.dtype(layout.plan.dtypes[i]))
           for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Shard-local ops — call INSIDE shard_map.
# ---------------------------------------------------------------------------

def local_chunks(tree, layout: Zero1Layout, axis_names: AxisNames):
    """This shard's contiguous 1/N chunk of every (padded, raveled) leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    idx = jax.lax.axis_index(axis_names)
    out = []
    for i, leaf in enumerate(leaves):
        c = layout.chunk_sizes[i]
        flat = _pad_flat(leaf, layout.padded_size(i))
        out.append(jax.lax.dynamic_slice_in_dim(flat, idx * c, c, 0))
    return jax.tree_util.tree_unflatten(treedef, out)


def reduce_scatter(tree, layout: Zero1Layout, axis_names: AxisNames, *,
                   payload_dtype=None):
    """Cross-shard SUM of every leaf, each shard keeping only its chunk.

    One ``psum_scatter`` per fusion bucket: the bucket's member leaves are
    packed as an ``(N, row)`` matrix whose row k holds every member's chunk
    k, so the tiled scatter over the raveled payload hands shard k exactly
    row k — its own chunk of every member — already reduced. This is the
    first half of the ring all-reduce with the all-gather elided.

    ``payload_dtype`` (bf16 compression) applies to the scatter payload
    only; chunks are restored to each leaf's own dtype immediately after.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    n = layout.axis_size
    out: list[Any] = [None] * len(leaves)
    tele = telemetry.get()
    for b, members in enumerate(layout.plan.buckets):
        # Same per-bucket annotation scheme as collectives.all_reduce:
        # named_scope for device profiles, a trace-time telemetry span
        # (cat="trace") for the Chrome trace.
        scope = f"zero1/reduce_scatter/bucket{b:02d}"
        with tele.span(f"collective:{scope}", cat="trace",
                       leaves=len(members)), jax.named_scope(scope):
            common = (jnp.dtype(payload_dtype) if payload_dtype is not None
                      else jnp.result_type(
                          *(layout.plan.dtypes[i] for i in members)))
            parts = []
            for i in members:
                flat = _pad_flat(leaves[i].astype(common),
                                 layout.padded_size(i))
                parts.append(flat.reshape(n, layout.chunk_sizes[i]))
            row = (parts[0] if len(parts) == 1
                   else jnp.concatenate(parts, axis=1))
            chunk = jax.lax.psum_scatter(row.reshape(-1), axis_names,
                                         scatter_dimension=0, tiled=True)
            off = 0
            for i in members:
                c = layout.chunk_sizes[i]
                piece = jax.lax.dynamic_slice_in_dim(chunk, off, c, 0)
                out[i] = piece.astype(layout.plan.dtypes[i])
                off += c
    return jax.tree_util.tree_unflatten(treedef, out)


def all_gather_chunks(chunks, layout: Zero1Layout, axis_names: AxisNames):
    """Reassemble full leaves from per-shard chunks (updated parameters).

    One ``all_gather`` per fusion bucket — the second half of the ring
    all-reduce, moved AFTER the optimizer update. The gathered ``(N*row,)``
    payload reshapes to ``(N, row)`` with row k = shard k's chunks; slicing
    a member's column block and raveling row-major restores its padded flat
    leaf in natural order.
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunks)
    _check_leaves(layout, len(leaves))
    n = layout.axis_size
    out: list[Any] = [None] * len(leaves)
    tele = telemetry.get()
    for b, members in enumerate(layout.plan.buckets):
        scope = f"zero1/all_gather/bucket{b:02d}"
        with tele.span(f"collective:{scope}", cat="trace",
                       leaves=len(members)), jax.named_scope(scope):
            common = jnp.result_type(
                *(layout.plan.dtypes[i] for i in members))
            parts = [leaves[i].astype(common) for i in members]
            row = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            full = jax.lax.all_gather(row, axis_names, tiled=True)
            mat = full.reshape(n, -1)
            off = 0
            for i in members:
                c = layout.chunk_sizes[i]
                shape = layout.plan.shapes[i]
                piece = jax.lax.slice_in_dim(mat, off, off + c, axis=1)
                out[i] = (piece.reshape(n * c)[:_numel(shape)]
                          .reshape(shape).astype(layout.plan.dtypes[i]))
                off += c
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Optimizer-state layout derivation. Which opt-state leaves mirror a
# parameter leaf (momentum, Adam moments — chunked and sharded) vs carry
# their own shape (step counters — replicated) is decided STRUCTURALLY: init
# the optimizer abstractly against two probe trees with different leaf sizes
# and mark the leaves whose shape follows the probe. Flatten order is
# identical across inits of the same treedef, so index i of the chunked
# template, the canonical template, and a live opt state all name the same
# leaf — no shape-based guessing (a 1-D bias can collide with its own
# padded-chunk length).
# ---------------------------------------------------------------------------

def _struct_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), jnp.dtype(x.dtype)),
        tree)


def _probe_struct(tree, layout: Zero1Layout):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [jax.ShapeDtypeStruct(
        (layout.padded_size(i) + layout.axis_size,),
        jnp.dtype(layout.plan.dtypes[i])) for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


def _opt_templates(tx, params_struct, layout: Zero1Layout):
    """(canonical flat, chunked flat, treedef, per-leaf chunked? mask)."""
    params_struct = _struct_tree(params_struct)
    canonical = jax.eval_shape(tx.init, params_struct)
    chunked = jax.eval_shape(tx.init, chunked_struct(params_struct, layout))
    probe = jax.eval_shape(tx.init, _probe_struct(params_struct, layout))
    flat_canon, tdef_c = jax.tree_util.tree_flatten(canonical)
    flat_chunk, tdef_k = jax.tree_util.tree_flatten(chunked)
    flat_probe, _ = jax.tree_util.tree_flatten(probe)
    if tdef_c != tdef_k:
        raise ValueError(
            "optimizer state structure depends on parameter leaf shapes; "
            "the ZeRO-1 chunked<->canonical correspondence needs it to be "
            f"shape-independent (canonical {tdef_c} vs chunked {tdef_k})")
    mask = tuple(k.shape != p.shape
                 for k, p in zip(flat_chunk, flat_probe))
    return flat_canon, flat_chunk, tdef_c, mask


def opt_state_specs(tx, params_struct, layout: Zero1Layout,
                    chunk_spec, replicated_spec):
    """Per-leaf PartitionSpec tree for the optimizer state: ``chunk_spec``
    on chunked (parameter-mirroring) leaves, ``replicated_spec`` elsewhere
    (step counters). Feeds shard_map in/out_specs and jit out_shardings."""
    _, _, treedef, mask = _opt_templates(tx, params_struct, layout)
    return jax.tree_util.tree_unflatten(
        treedef, [chunk_spec if m else replicated_spec for m in mask])


class Zero1StateConverter:
    """Gather-on-save / reshard-on-restore for the chunked optimizer state.

    ``to_canonical`` strips padding and restores each chunked opt-state
    leaf to its parameter's shape — the exact layout the replicated path
    saves, so checkpoints are interchangeable between ``none`` and
    ``zero1`` and across DP degrees. ``from_canonical`` re-pads for the
    CURRENT layout and places chunk leaves sharded over the DP axes.
    ``canonical_abstract`` describes the on-disk layout for orbax's
    structure-matched restore (replicated placement; the reshard happens in
    ``from_canonical`` right after).
    """

    def __init__(self, tx, params_struct, layout: Zero1Layout, mesh,
                 axis_names: AxisNames):
        self.layout = layout
        self._flat_canon, self._flat_chunk, self._treedef, self._mask = (
            _opt_templates(tx, params_struct, layout))
        self._rep = NamedSharding(mesh, P())
        self._chunk_shd = NamedSharding(mesh, P(axis_names))

    def _flat(self, opt_state):
        flat, treedef = jax.tree_util.tree_flatten(opt_state)
        if treedef != self._treedef:
            raise ValueError(
                f"optimizer state structure does not match the converter's "
                f"template: {treedef} vs {self._treedef}")
        return flat

    def _opt_to_canonical(self, opt_state):
        out = []
        for leaf, m, canon in zip(self._flat(opt_state), self._mask,
                                  self._flat_canon):
            out.append(leaf[:_numel(canon.shape)].reshape(canon.shape)
                       if m else leaf)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _opt_from_canonical(self, opt_state):
        out = []
        for leaf, m, chunk in zip(self._flat(opt_state), self._mask,
                                  self._flat_chunk):
            out.append(_pad_flat(leaf, chunk.shape[0]) if m else leaf)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def opt_shardings(self):
        return jax.tree_util.tree_unflatten(
            self._treedef,
            [self._chunk_shd if m else self._rep for m in self._mask])

    def to_canonical(self, state):
        """TrainState with the opt state gathered to canonical layout."""
        return jax.jit(lambda s: s.replace(
            opt_state=self._opt_to_canonical(s.opt_state)))(state)

    def from_canonical(self, state):
        """TrainState with the opt state padded + sharded for this layout."""
        shardings = jax.tree_util.tree_map(lambda _: self._rep, state)
        shardings = shardings.replace(opt_state=self.opt_shardings())
        return jax.jit(
            lambda s: s.replace(
                opt_state=self._opt_from_canonical(s.opt_state)),
            out_shardings=shardings)(state)

    def canonical_abstract(self, state_like):
        """``state_like`` with the opt state replaced by the canonical
        (on-disk) layout as sharding-carrying ShapeDtypeStructs."""
        out = []
        for leaf, m, canon in zip(self._flat(state_like.opt_state),
                                  self._mask, self._flat_canon):
            if m:
                out.append(jax.ShapeDtypeStruct(canon.shape, canon.dtype,
                                                sharding=self._rep))
            else:
                out.append(jax.ShapeDtypeStruct(
                    tuple(leaf.shape), leaf.dtype,
                    sharding=getattr(leaf, "sharding", self._rep)))
        return state_like.replace(opt_state=jax.tree_util.tree_unflatten(
            self._treedef, out))
