"""ZeRO sharding ladder (stages 1-3) for the explicit-DP path.

The bucketed ring all-reduce (parallel/collectives.py) already materializes
the ZeRO-1 partition as its intermediate: after ``psum_scatter`` each shard
holds the reduced 1/N chunk of every bucket, and the trailing ``all_gather``
throws that structure away so every shard can run the SAME full optimizer
update. ZeRO-1 (ZeRO stage 1, Rajbhandari et al.) keeps it instead: the
optimizer update runs on each shard's chunk only, optimizer state lives
permanently 1/N-sharded, and the ``all_gather`` moves the *updated
parameters* rather than the summed gradients — identical communication
volume (one reduce-scatter + one all-gather of the parameter bytes per
step), optimizer HBM and update FLOPs divided by the DP degree.

The higher stages extend the SAME chunk layout (train/steps.py selects the
schedule per ``TrainConfig.optimizer_sharding``):

- **ZeRO-2** — gradients are born reduce-scattered: a per-bucket identity
  ``custom_vjp`` (:func:`assemble_params_overlapped`) makes the loss
  differentiate w.r.t. this shard's parameter CHUNKS, its backward rule
  reduce-scattering each bucket's parameter cotangents the moment backward
  produces them. The full gradient tree is never materialized as a live
  whole and the collectives overlap the remaining backward compute —
  update arithmetic identical to zero1 (same packed per-bucket
  ``psum_scatter``, same chunk update).
- **ZeRO-3 / FSDP-unified** — parameters themselves live 1/N-chunked and
  are all-gathered on demand per fusion bucket for forward/backward
  (:func:`gather_params_overlapped`); the backward rule of that gather is
  the bucket reduce-scatter, so gradient chunks come out of autodiff
  already reduced, overlapped with backward. This folds the GSPMD
  ``fsdp`` parameter-sharding rule (parallel/sharding.py) into the
  explicit path's bucket planner — an image config with ``fsdp > 1`` plus
  ``zero3`` shards chunks over BOTH dp axes.

Layout: per-leaf chunking that PRESERVES the parameter treedef. Every leaf
is raveled, zero-padded to a multiple of the axis size N, and split into N
contiguous chunks; shard k owns elements ``[k*c, (k+1)*c)`` of every leaf.
Keeping one chunk per leaf (instead of slicing the concatenated bucket)
means the chunk tree has the same structure and relative magnitudes as the
parameter tree, so path-keyed weight-decay masks apply unchanged and
per-layer trust-ratio norms (LARS/LAMB) need only a cross-shard ``psum`` of
squared sums (train/optim.py) to be exact. Bucket fusion is kept at the
collective level: each fusion bucket's member leaves are packed into ONE
``(N, row)`` payload — row k carrying every member's chunk k — so one
``psum_scatter``/``all_gather`` launches per bucket, exactly like the fused
all-reduce.

Padding is benign through every supported optimizer: padded gradient
elements are zero on all shards, so momentum/Adam moments stay zero, the
update there is zero, and squared-sum norms gain nothing.

Checkpoint compatibility (train/checkpoint.py): :class:`Zero1StateConverter`
gathers the chunked optimizer state into the CANONICAL layout — each leaf
restored to its parameter's shape, padding stripped — before save, and pads
and re-shards on restore. The canonical layout is byte-identical to what the
replicated path saves, so zero1 checkpoints restore replicated, replicated
checkpoints restore into zero1, and the DP degree may change between save
and resume (the pad is a function of N and is never persisted).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu.observability import flight, telemetry
from distributeddeeplearning_tpu.parallel.collectives import (
    _MB, AxisNames, BucketPlan, DEFAULT_BUCKET_MB, _numel, plan_buckets)


@dataclasses.dataclass(frozen=True)
class Zero1Layout:
    """Chunk assignment for ONE parameter tree shape on ONE axis size.

    ``chunk_sizes[i]`` is the per-shard chunk length of flatten-order leaf
    i: ``ceil(numel_i / axis_size)``; the leaf's padded flat length is
    ``chunk_sizes[i] * axis_size``. Bucket membership reuses the
    deterministic path-keyed planner, so the payload layout is stable under
    dict insertion-order churn exactly like the fused all-reduce.
    """

    plan: BucketPlan
    axis_size: int
    chunk_sizes: tuple[int, ...]

    @property
    def num_leaves(self) -> int:
        return self.plan.num_leaves

    def padded_size(self, i: int) -> int:
        return self.chunk_sizes[i] * self.axis_size

    def describe(self) -> str:
        total = sum(_numel(s) for s in self.plan.shapes)
        padded = sum(self.padded_size(i) for i in range(self.num_leaves))
        return (f"1/{self.axis_size} per shard over "
                f"{len(self.plan.buckets)} bucket(s), "
                f"{self.num_leaves} leaves, pad {padded - total} elems")


def stage_index(optimizer_sharding: Optional[str]) -> int:
    """The ZeRO stage number of an ``--optimizer-sharding`` mode (none -> 0,
    zero1 -> 1, ...). Used by cross-axis elastic re-formation to describe a
    stage change (``zero2 -> none``) in resume announcements and sidecars —
    the canonical on-disk layout is stage-agnostic, so any pair is legal."""
    mode = (optimizer_sharding or "none").strip().lower()
    if mode in ("", "none"):
        return 0
    if mode.startswith("zero") and mode[4:].isdigit():
        return int(mode[4:])
    raise ValueError(f"unknown optimizer-sharding mode {optimizer_sharding!r}")


def build_layout(tree, axis_size: int,
                 bucket_bytes: Optional[int] = None) -> Zero1Layout:
    """Plan the ZeRO-1 chunk layout for ``tree`` (arrays or shape structs —
    shapes are static, so this works on tracers at trace time)."""
    if axis_size < 1:
        raise ValueError(f"axis_size must be >= 1 (got {axis_size})")
    plan = plan_buckets(tree, bucket_bytes)
    chunk_sizes = tuple(-(-_numel(s) // axis_size) for s in plan.shapes)
    return Zero1Layout(plan=plan, axis_size=axis_size,
                       chunk_sizes=chunk_sizes)


def payload_dtype_from_options(options=None) -> Optional[Any]:
    """Gradient-scatter payload dtype per the run's AllReduceConfig (None =
    reduce in the gradients' own dtype, ``jnp.bfloat16`` = compressed
    wire payload). Shared by every stage's scatter path."""
    dtype_name = getattr(options, "dtype", "float32") or "float32"
    if dtype_name not in ("float32", "bfloat16"):
        raise ValueError(
            f"allreduce dtype {dtype_name!r} not supported; use 'float32' "
            f"(reduce in the gradients' own dtype) or 'bfloat16' "
            f"(compressed payload, fp32 master restored after the reduce)")
    return jnp.bfloat16 if dtype_name == "bfloat16" else None


def layout_from_options(tree, axis_size: int, options=None
                        ) -> tuple[Zero1Layout, Optional[Any]]:
    """(layout, scatter payload dtype) per the run's AllReduceConfig —
    the same bucket-size/dtype policy knobs the fused all-reduce reads.
    The payload dtype applies to the gradient reduce-scatter only; the
    parameter all-gather always moves the parameters' own dtype."""
    bucket_mb = getattr(options, "bucket_mb", DEFAULT_BUCKET_MB)
    payload = payload_dtype_from_options(options)
    return build_layout(tree, axis_size,
                        int(float(bucket_mb) * _MB)), payload


def modeled_grad_bytes(layout: Zero1Layout, *, chunked: bool) -> int:
    """Per-device gradient residency MODEL for the memory-ladder accounting
    (gradients are transient, so unlike params/opt-state they cannot be
    measured off a held state tree): full leaf bytes for schedules that
    materialize the whole gradient tree (replicated, zero1, overlap-off
    zero2/zero3), chunk bytes when gradients only ever exist
    reduce-scattered (overlapped zero2/zero3)."""
    plan = layout.plan
    if chunked:
        return sum(c * jnp.dtype(plan.dtypes[i]).itemsize
                   for i, c in enumerate(layout.chunk_sizes))
    return sum(_numel(s) * jnp.dtype(plan.dtypes[i]).itemsize
               for i, s in enumerate(plan.shapes))


def _check_leaves(layout: Zero1Layout, n: int) -> None:
    if n != layout.num_leaves:
        raise ValueError(f"layout was built for {layout.num_leaves} leaves, "
                         f"tree has {n}")


def _pad_flat(leaf, padded: int):
    flat = leaf.ravel()
    pad = padded - flat.size
    return jnp.pad(flat, (0, pad)) if pad else flat


# ---------------------------------------------------------------------------
# Global (full-array) layout conversions — used for optimizer-state init and
# checkpoint reshard, OUTSIDE shard_map. The chunked global form of a leaf is
# its zero-padded ravel of length chunk*N; placed with P(data, fsdp) on dim 0
# it is exactly the concatenation of the shards' chunks.
# ---------------------------------------------------------------------------

def to_chunked(tree, layout: Zero1Layout):
    """Each leaf -> its padded flat ``(chunk * N,)`` global form."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = [_pad_flat(leaf, layout.padded_size(i))
           for i, leaf in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def from_chunked(tree, layout: Zero1Layout):
    """Inverse of :func:`to_chunked`: strip padding, restore leaf shapes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = []
    for i, leaf in enumerate(leaves):
        shape = layout.plan.shapes[i]
        out.append(leaf[:_numel(shape)].reshape(shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def chunked_struct(tree, layout: Zero1Layout):
    """ShapeDtypeStruct tree of the chunked global form (for eval_shape)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out = [jax.ShapeDtypeStruct((layout.padded_size(i),),
                                jnp.dtype(layout.plan.dtypes[i]))
           for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Shard-local ops — call INSIDE shard_map.
# ---------------------------------------------------------------------------

def local_chunks(tree, layout: Zero1Layout, axis_names: AxisNames):
    """This shard's contiguous 1/N chunk of every (padded, raveled) leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    idx = jax.lax.axis_index(axis_names)
    out = []
    for i, leaf in enumerate(leaves):
        c = layout.chunk_sizes[i]
        flat = _pad_flat(leaf, layout.padded_size(i))
        out.append(jax.lax.dynamic_slice_in_dim(flat, idx * c, c, 0))
    return jax.tree_util.tree_unflatten(treedef, out)


def _scatter_members(fulls, layout: Zero1Layout, axis_names: AxisNames,
                     b: int, payload_dtype=None, scope_prefix: str = "zero1",
                     overlapped: bool = False) -> tuple:
    """One bucket's reduce-scatter: full-shaped member leaves (ordered as
    ``layout.plan.buckets[b]``) -> that bucket's reduced chunk leaves.

    The bucket's members are packed as an ``(N, row)`` matrix whose row k
    holds every member's chunk k, so the tiled ``psum_scatter`` over the
    raveled payload hands shard k exactly row k — its own chunk of every
    member — already reduced. ``overlapped=True`` marks the trace-time
    span for :func:`telemetry.overlap_fraction` — it is set only by the
    custom_vjp backward rules, where the scatter is issued inside backward.
    """
    members = layout.plan.buckets[b]
    n = layout.axis_size
    tele = telemetry.get()
    # Same per-bucket annotation scheme as collectives.all_reduce:
    # named_scope for device profiles, a trace-time telemetry span
    # (cat="trace") for the Chrome trace.
    scope = f"{scope_prefix}/reduce_scatter/bucket{b:02d}"
    span_args = {"cat": "trace", "leaves": len(members)}
    if overlapped:
        span_args["overlapped"] = True
    # Flight-record mirror of the trace span: this body runs once per
    # COMPILE (trace time), so the record gets a one-shot collective-plan
    # event per bucket, never a per-step fsync.
    flight.get().record("collective", phase="reduce_scatter", scope=scope,
                        bucket=b, leaves=len(members),
                        overlapped=bool(overlapped))
    with tele.span(f"collective:{scope}", **span_args), \
            jax.named_scope(scope):
        common = (jnp.dtype(payload_dtype) if payload_dtype is not None
                  else jnp.result_type(
                      *(layout.plan.dtypes[i] for i in members)))
        parts = []
        for j, i in enumerate(members):
            flat = _pad_flat(fulls[j].astype(common), layout.padded_size(i))
            parts.append(flat.reshape(n, layout.chunk_sizes[i]))
        row = (parts[0] if len(parts) == 1
               else jnp.concatenate(parts, axis=1))
        chunk = jax.lax.psum_scatter(row.reshape(-1), axis_names,
                                     scatter_dimension=0, tiled=True)
        out = []
        off = 0
        for i in members:
            c = layout.chunk_sizes[i]
            piece = jax.lax.dynamic_slice_in_dim(chunk, off, c, 0)
            out.append(piece.astype(layout.plan.dtypes[i]))
            off += c
    return tuple(out)


def _gather_members(chunks, layout: Zero1Layout, axis_names: AxisNames,
                    b: int, scope_prefix: str = "zero1",
                    out_dtype=None) -> tuple:
    """One bucket's all-gather: chunk member leaves (ordered as
    ``layout.plan.buckets[b]``) -> full-shaped member leaves. The gathered
    ``(N*row,)`` payload reshapes to ``(N, row)`` with row k = shard k's
    chunks; slicing a member's column block and raveling row-major
    restores its padded flat leaf in natural order.

    ``out_dtype`` (mixed precision, zero3): cast each chunk to the compute
    dtype BEFORE the collective — halving the wire bytes when the masters
    are fp32 and compute is bf16 — and leave the gathered full leaves in
    that dtype instead of restoring the plan (master) dtypes."""
    members = layout.plan.buckets[b]
    n = layout.axis_size
    tele = telemetry.get()
    scope = f"{scope_prefix}/all_gather/bucket{b:02d}"
    flight.get().record("collective", phase="all_gather", scope=scope,
                        bucket=b, leaves=len(members))
    with tele.span(f"collective:{scope}", cat="trace",
                   leaves=len(members)), jax.named_scope(scope):
        if out_dtype is not None:
            common = jnp.dtype(out_dtype)
        else:
            common = jnp.result_type(
                *(layout.plan.dtypes[i] for i in members))
        parts = [chunks[j].astype(common) for j in range(len(members))]
        row = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        full = jax.lax.all_gather(row, axis_names, tiled=True)
        mat = full.reshape(n, -1)
        out = []
        off = 0
        for i in members:
            c = layout.chunk_sizes[i]
            shape = layout.plan.shapes[i]
            piece = jax.lax.slice_in_dim(mat, off, off + c, axis=1)
            leaf_dtype = (out_dtype if out_dtype is not None
                          else layout.plan.dtypes[i])
            out.append(piece.reshape(n * c)[:_numel(shape)]
                       .reshape(shape).astype(leaf_dtype))
            off += c
    return tuple(out)


def reduce_scatter(tree, layout: Zero1Layout, axis_names: AxisNames, *,
                   payload_dtype=None):
    """Cross-shard SUM of every leaf, each shard keeping only its chunk.

    One ``psum_scatter`` per fusion bucket (see :func:`_scatter_members`) —
    the first half of the ring all-reduce with the all-gather elided.

    ``payload_dtype`` (bf16 compression) applies to the scatter payload
    only; chunks are restored to each leaf's own dtype immediately after.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    _check_leaves(layout, len(leaves))
    out: list[Any] = [None] * len(leaves)
    for b, members in enumerate(layout.plan.buckets):
        pieces = _scatter_members([leaves[i] for i in members], layout,
                                  axis_names, b, payload_dtype)
        for i, piece in zip(members, pieces):
            out[i] = piece
    return jax.tree_util.tree_unflatten(treedef, out)


def all_gather_chunks(chunks, layout: Zero1Layout, axis_names: AxisNames,
                      *, out_dtype=None):
    """Reassemble full leaves from per-shard chunks (updated parameters).

    One ``all_gather`` per fusion bucket (see :func:`_gather_members`) —
    the second half of the ring all-reduce, moved AFTER the optimizer
    update. ``out_dtype`` casts before the wire and skips the restore to
    master dtypes (mixed-precision zero3 forward gathers).
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunks)
    _check_leaves(layout, len(leaves))
    out: list[Any] = [None] * len(leaves)
    for b, members in enumerate(layout.plan.buckets):
        pieces = _gather_members([leaves[i] for i in members], layout,
                                 axis_names, b, out_dtype=out_dtype)
        for i, piece in zip(members, pieces):
            out[i] = piece
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Backward/collective overlap (ZeRO-2/3). Each fusion bucket gets its OWN
# custom_vjp boundary, so in the backward pass bucket b's reduce-scatter
# depends only on bucket b's parameter cotangents — XLA issues it the moment
# those are produced, while backward continues through earlier layers. A
# single tree-level vjp (or the post-backward reduce_scatter above) would
# serialize every collective after the last cotangent instead.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _gather_vjp(layout: Zero1Layout, axis_names, b: int, payload_dtype,
                scope_prefix: str, out_dtype=None):
    """ZeRO-3 bucket primitive: fwd all-gathers this shard's chunks into
    full leaves (in ``out_dtype`` when set — bf16 compute params from fp32
    masters, cast before the wire); bwd reduce-scatters the full-shaped
    cotangents back to chunk cotangents in the plan (master) dtypes (the
    exact transpose of a tiled all-gather whose output feeds every shard's
    loss term)."""

    def _primal(*chunks):
        return _gather_members(chunks, layout, axis_names, b, scope_prefix,
                               out_dtype=out_dtype)

    def _fwd(*chunks):
        return _primal(*chunks), None

    def _bwd(_, cts):
        return _scatter_members(cts, layout, axis_names, b, payload_dtype,
                                scope_prefix, overlapped=True)

    fn = jax.custom_vjp(_primal)
    fn.defvjp(_fwd, _bwd)
    return fn


@functools.lru_cache(maxsize=None)
def _assemble_vjp(layout: Zero1Layout, axis_names, b: int, payload_dtype):
    """ZeRO-2 bucket primitive: fwd is the IDENTITY on the already-
    replicated full leaves (the chunk operands are unused — parameters are
    not sharded at stage 2, so no forward gather is owed); bwd
    reduce-scatters the full-shaped cotangents into the CHUNK operands'
    cotangent slots. Differentiating a loss w.r.t. the chunks therefore
    yields already-reduce-scattered gradients without the full gradient
    tree ever forming, at zero forward cost. The full-leaf operands get
    zero cotangents — they enter as non-differentiated closure constants
    in train/steps.py, so those zeros are dead code XLA eliminates."""
    members = layout.plan.buckets[b]
    nm = len(members)

    def _primal(*args):
        return args[:nm]

    def _fwd(*args):
        return args[:nm], None

    def _bwd(_, cts):
        gchunks = _scatter_members(cts, layout, axis_names, b, payload_dtype,
                                   "zero2", overlapped=True)
        zeros = tuple(jnp.zeros(layout.plan.shapes[i],
                                layout.plan.dtypes[i]) for i in members)
        return zeros + gchunks

    fn = jax.custom_vjp(_primal)
    fn.defvjp(_fwd, _bwd)
    return fn


def _as_axis_key(axis_names: AxisNames):
    return axis_names if isinstance(axis_names, str) else tuple(axis_names)


def _dtype_key(dtype):
    """Hashable, canonical form of an optional dtype for the lru_cached
    vjp factories (np scalar types and jnp.dtype objects must alias)."""
    return None if dtype is None else jnp.dtype(dtype).name


def gather_params_overlapped(pchunks, layout: Zero1Layout,
                             axis_names: AxisNames, *, payload_dtype=None,
                             scope_prefix: str = "zero3", out_dtype=None):
    """ZeRO-3 on-demand parameter materialization with backward overlap.

    Assembles the full parameter tree from this shard's chunk tree, one
    custom_vjp all-gather per fusion bucket. Differentiating a loss through
    the result w.r.t. ``pchunks`` yields ALREADY reduce-scattered chunk
    gradients (cross-shard SUM — divide by N for the average), each
    bucket's scatter issued inside backward as its cotangents complete.
    """
    leaves, treedef = jax.tree_util.tree_flatten(pchunks)
    _check_leaves(layout, len(leaves))
    out: list[Any] = [None] * len(leaves)
    key = _as_axis_key(axis_names)
    for b, members in enumerate(layout.plan.buckets):
        fn = _gather_vjp(layout, key, b, payload_dtype, scope_prefix,
                         _dtype_key(out_dtype))
        fulls = fn(*[leaves[i] for i in members])
        for i, full in zip(members, fulls):
            out[i] = full
    return jax.tree_util.tree_unflatten(treedef, out)


def assemble_params_overlapped(params, pchunks, layout: Zero1Layout,
                               axis_names: AxisNames, *, payload_dtype=None):
    """ZeRO-2 gradient-scatter boundary: returns ``params`` unchanged
    (identity forward — parameters stay replicated at stage 2) wired so
    that differentiating a loss through the result w.r.t. ``pchunks``
    yields reduce-scattered bucket gradients issued during backward.
    ``params`` must enter as a non-differentiated constant of the loss."""
    pleaves, treedef = jax.tree_util.tree_flatten(params)
    cleaves, _ = jax.tree_util.tree_flatten(pchunks)
    _check_leaves(layout, len(pleaves))
    _check_leaves(layout, len(cleaves))
    out: list[Any] = [None] * len(pleaves)
    key = _as_axis_key(axis_names)
    for b, members in enumerate(layout.plan.buckets):
        fn = _assemble_vjp(layout, key, b, payload_dtype)
        fulls = fn(*([pleaves[i] for i in members]
                     + [cleaves[i] for i in members]))
        for i, full in zip(members, fulls):
            out[i] = full
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Optimizer-state layout derivation. Which opt-state leaves mirror a
# parameter leaf (momentum, Adam moments — chunked and sharded) vs carry
# their own shape (step counters — replicated) is decided STRUCTURALLY: init
# the optimizer abstractly against two probe trees with different leaf sizes
# and mark the leaves whose shape follows the probe. Flatten order is
# identical across inits of the same treedef, so index i of the chunked
# template, the canonical template, and a live opt state all name the same
# leaf — no shape-based guessing (a 1-D bias can collide with its own
# padded-chunk length).
# ---------------------------------------------------------------------------

def _struct_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), jnp.dtype(x.dtype)),
        tree)


def _probe_struct(tree, layout: Zero1Layout):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [jax.ShapeDtypeStruct(
        (layout.padded_size(i) + layout.axis_size,),
        jnp.dtype(layout.plan.dtypes[i])) for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


def _opt_templates(tx, params_struct, layout: Zero1Layout):
    """(canonical flat, chunked flat, treedef, per-leaf chunked? mask)."""
    params_struct = _struct_tree(params_struct)
    canonical = jax.eval_shape(tx.init, params_struct)
    chunked = jax.eval_shape(tx.init, chunked_struct(params_struct, layout))
    probe = jax.eval_shape(tx.init, _probe_struct(params_struct, layout))
    flat_canon, tdef_c = jax.tree_util.tree_flatten(canonical)
    flat_chunk, tdef_k = jax.tree_util.tree_flatten(chunked)
    flat_probe, _ = jax.tree_util.tree_flatten(probe)
    if tdef_c != tdef_k:
        raise ValueError(
            "optimizer state structure depends on parameter leaf shapes; "
            "the ZeRO-1 chunked<->canonical correspondence needs it to be "
            f"shape-independent (canonical {tdef_c} vs chunked {tdef_k})")
    mask = tuple(k.shape != p.shape
                 for k, p in zip(flat_chunk, flat_probe))
    return flat_canon, flat_chunk, tdef_c, mask


def opt_state_specs(tx, params_struct, layout: Zero1Layout,
                    chunk_spec, replicated_spec):
    """Per-leaf PartitionSpec tree for the optimizer state: ``chunk_spec``
    on chunked (parameter-mirroring) leaves, ``replicated_spec`` elsewhere
    (step counters). Feeds shard_map in/out_specs and jit out_shardings."""
    _, _, treedef, mask = _opt_templates(tx, params_struct, layout)
    return jax.tree_util.tree_unflatten(
        treedef, [chunk_spec if m else replicated_spec for m in mask])


class ZeroStateConverter:
    """Gather-on-save / reshard-on-restore between a stage's live layout
    and the CANONICAL (replicated-path) checkpoint layout.

    ``to_canonical`` strips padding and restores each chunked leaf to its
    parameter's shape — the exact layout the replicated path saves, so
    checkpoints are interchangeable across ``none``/``zero1``/``zero2``/
    ``zero3`` and across DP degrees (the pad is a function of N and never
    persisted). ``from_canonical`` re-pads for the CURRENT layout and
    places chunk leaves sharded over the DP axes. ``canonical_abstract``
    describes the on-disk layout for orbax's structure-matched restore
    (replicated placement; the reshard happens in ``from_canonical`` right
    after).

    ``stage`` selects WHICH trees are chunked in the live layout: the
    optimizer state for every stage (1-3 share the zero1 opt layout;
    stage 2's difference — never-materialized gradients — is transient and
    has no checkpoint footprint), plus params/ema_params at stage 3, where
    parameters live in the chunked global form.
    """

    def __init__(self, tx, params_struct, layout: Zero1Layout, mesh,
                 axis_names: AxisNames, stage: int = 1,
                 opt_memory_kind: Optional[str] = None):
        if stage not in (1, 2, 3):
            raise ValueError(f"stage must be 1, 2 or 3 (got {stage})")
        self.layout = layout
        self.stage = stage
        self.opt_memory_kind = opt_memory_kind
        self._params_struct = _struct_tree(params_struct)
        self._flat_canon, self._flat_chunk, self._treedef, self._mask = (
            _opt_templates(tx, params_struct, layout))
        self._rep = NamedSharding(mesh, P())
        self._chunk_shd = NamedSharding(mesh, P(axis_names))
        # Host-RAM offload (--opt-state-offload): the chunked opt-state
        # leaves carry a host memory kind; params/ema chunk placements
        # (stage 3) stay in device memory — they're touched every fwd/bwd.
        self._opt_chunk_shd = (self._chunk_shd.with_memory_kind(
            opt_memory_kind) if opt_memory_kind else self._chunk_shd)
        self._full_params_jit = None

    def _flat(self, opt_state):
        flat, treedef = jax.tree_util.tree_flatten(opt_state)
        if treedef != self._treedef:
            raise ValueError(
                f"optimizer state structure does not match the converter's "
                f"template: {treedef} vs {self._treedef}")
        return flat

    def _opt_to_canonical(self, opt_state):
        out = []
        for leaf, m, canon in zip(self._flat(opt_state), self._mask,
                                  self._flat_canon):
            out.append(leaf[:_numel(canon.shape)].reshape(canon.shape)
                       if m else leaf)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _opt_from_canonical(self, opt_state):
        out = []
        for leaf, m, chunk in zip(self._flat(opt_state), self._mask,
                                  self._flat_chunk):
            out.append(_pad_flat(leaf, chunk.shape[0]) if m else leaf)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def opt_shardings(self):
        return jax.tree_util.tree_unflatten(
            self._treedef,
            [self._opt_chunk_shd if m else self._rep for m in self._mask])

    def param_shardings(self, tree):
        """Chunk shardings for a params-shaped tree (stage-3 live layout)."""
        return jax.tree_util.tree_map(lambda _: self._chunk_shd, tree)

    @property
    def _params_chunked(self) -> bool:
        return self.stage >= 3

    def _live_to_canonical(self, s):
        s = s.replace(opt_state=self._opt_to_canonical(s.opt_state))
        if self._params_chunked:
            s = s.replace(params=from_chunked(s.params, self.layout))
            if s.ema_params is not None:
                s = s.replace(
                    ema_params=from_chunked(s.ema_params, self.layout))
        return s

    def to_canonical(self, state):
        """TrainState with every chunked tree gathered to canonical layout."""
        if self._params_chunked:
            # Pin EVERY output replicated — canonical means full shapes,
            # opt state included; without out_shardings the pad-strip
            # reshape could keep a sharded placement that the canonical
            # (on-disk) layout does not admit.
            shardings = jax.tree_util.tree_map(lambda _: self._rep, state)
            return jax.jit(self._live_to_canonical,
                           out_shardings=shardings)(state)
        return jax.jit(self._live_to_canonical)(state)

    def from_canonical(self, state):
        """TrainState re-padded + sharded for this stage's live layout."""
        shardings = jax.tree_util.tree_map(lambda _: self._rep, state)
        shardings = shardings.replace(opt_state=self.opt_shardings())
        if self._params_chunked:
            shardings = shardings.replace(
                params=self.param_shardings(state.params))
            if state.ema_params is not None:
                shardings = shardings.replace(
                    ema_params=self.param_shardings(state.ema_params))

        def _pad(s):
            s = s.replace(opt_state=self._opt_from_canonical(s.opt_state))
            if self._params_chunked:
                s = s.replace(params=to_chunked(s.params, self.layout))
                if s.ema_params is not None:
                    s = s.replace(
                        ema_params=to_chunked(s.ema_params, self.layout))
            return s

        return jax.jit(_pad, out_shardings=shardings)(state)

    def full_params_state(self, state):
        """``state`` with FULL-shape (canonical) params/ema for consumers
        that need the whole model resident — evaluation, export. Identity
        below stage 3; at stage 3 a cached jit gathers the chunked global
        form back to parameter shapes (pure reshape+slice: the chunked
        global form holds every element, just padded and raveled)."""
        if not self._params_chunked:
            return state
        if self._full_params_jit is None:
            def _full(s):
                s = s.replace(params=from_chunked(s.params, self.layout))
                if s.ema_params is not None:
                    s = s.replace(
                        ema_params=from_chunked(s.ema_params, self.layout))
                return s
            self._full_params_jit = jax.jit(_full)
        return self._full_params_jit(state)

    def _abstract_full(self, tree):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(tuple(x.shape),
                                           jnp.dtype(x.dtype),
                                           sharding=self._rep), tree)

    def canonical_abstract(self, state_like):
        """``state_like`` with every chunked tree replaced by the canonical
        (on-disk) layout as sharding-carrying ShapeDtypeStructs."""
        out = []
        for leaf, m, canon in zip(self._flat(state_like.opt_state),
                                  self._mask, self._flat_canon):
            if m:
                out.append(jax.ShapeDtypeStruct(canon.shape, canon.dtype,
                                                sharding=self._rep))
            else:
                out.append(jax.ShapeDtypeStruct(
                    tuple(leaf.shape), leaf.dtype,
                    sharding=getattr(leaf, "sharding", self._rep)))
        state_like = state_like.replace(
            opt_state=jax.tree_util.tree_unflatten(self._treedef, out))
        if self._params_chunked:
            state_like = state_like.replace(
                params=self._abstract_full(self._params_struct))
            if state_like.ema_params is not None:
                state_like = state_like.replace(
                    ema_params=self._abstract_full(self._params_struct))
        return state_like


# Name retained from the ZeRO-1-only era (PR 2); external callers and
# checkpoints are agnostic to which stage produced a canonical save.
Zero1StateConverter = ZeroStateConverter
