"""Bucketed (fused) gradient all-reduce for the explicit-DP path.

Horovod's tensor fusion exists because reducing a CNN's gradient pytree
leaf-by-leaf issues one collective per parameter tensor — ResNet50 has ~160
leaves, many under 10 KB, so launch/latency overhead dominates the wire time
(Horovod, PAPERS.md:5). Batching small tensors into a few size-targeted
buckets amortizes that overhead and is the enabler for overlapping the
reduction with the tail of the backward pass (CUDA-aware-MPI DNN training,
PAPERS.md:6). This module is the XLA-native port of that idea for the
``shard_map`` DP path (train/steps.py):

- :func:`plan_buckets` flattens the gradient tree into deterministic,
  size-targeted fusion buckets. Assignment is keyed by the leaf's *tree
  path* (sorted), not by flatten order, so the plan is stable under dict
  insertion-order churn — the same leaf always lands in the same bucket.
- :func:`all_reduce` performs ONE collective per bucket: ``psum``, or the
  bandwidth-optimal ring form ``psum_scatter`` + ``all_gather``. Buckets
  are independent dataflow, so XLA's scheduler is free to start a bucket's
  collective the moment its last leaf's gradient is produced, overlapping
  communication with the remaining backward computation — the role of
  Horovod's background fusion-buffer thread, collapsed into one XLA
  program.
- A dtype policy (``payload_dtype``) optionally compresses the reduction
  payload to bf16 (half the wire bytes); results are immediately restored
  to each leaf's own dtype, so fp32 master params/optimizer state never
  see bf16 accumulation error beyond the documented reduce tolerance
  (docs/fused_allreduce.md).

Per-leaf reduction (``bucket_bytes=0``) is kept as the A/B reference path —
bench.py's ``ar_fused`` vs ``ar_perleaf`` suite rows measure exactly this
module's win on chip.

The bucket independence noted above is ALSO what the ZeRO-2/3 overlapped
schedules (parallel/zero.py) exploit: each fusion bucket gets its own
``custom_vjp`` boundary so its reduce-scatter depends only on that bucket's
cotangents, letting XLA issue it while earlier layers' backward is still
running.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.observability import telemetry

AxisNames = Union[str, tuple[str, ...]]

DEFAULT_BUCKET_MB = 4.0
_MB = 1024 * 1024


def _numel(shape: Sequence[int]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _path_str(path) -> str:
    keystr = getattr(jax.tree_util, "keystr", None)
    if keystr is not None:
        return keystr(path)
    return "/".join(str(k) for k in path)  # pragma: no cover - old jax


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """A deterministic leaf -> fusion-bucket assignment for ONE tree shape.

    ``buckets`` holds groups of indices into the *flatten-order* leaf list;
    group order and membership derive only from (path, shape, dtype), never
    from flatten order, so two trees with the same leaves produce the same
    plan regardless of container insertion order.
    """

    treedef: Any
    paths: tuple[str, ...]                 # per flatten-order leaf
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    buckets: tuple[tuple[int, ...], ...]   # flatten-order indices per bucket
    bucket_bytes: int

    @property
    def num_leaves(self) -> int:
        return len(self.paths)

    def bucket_of(self, path: str) -> int:
        """Bucket index holding the leaf at ``path`` (stability tests)."""
        i = self.paths.index(path)
        for b, members in enumerate(self.buckets):
            if i in members:
                return b
        raise KeyError(path)  # pragma: no cover - every leaf is assigned

    def describe(self) -> str:
        sizes = [sum(_numel(self.shapes[i]) for i in members)
                 for members in self.buckets]
        return (f"{len(self.buckets)} bucket(s) over {self.num_leaves} "
                f"leaves, elems/bucket={sizes}")


def plan_buckets(tree, bucket_bytes: Optional[int] = None) -> BucketPlan:
    """Assign the leaves of ``tree`` (arrays OR shape/dtype structs — works
    on tracers at trace time) to size-targeted fusion buckets.

    Leaves are visited in sorted-path order and packed greedily: a bucket
    closes when adding the next leaf would push it past ``bucket_bytes``
    (a single oversized leaf still gets its own bucket). ``bucket_bytes``
    <= 0 degenerates to one bucket per leaf — the unfused reference plan.
    """
    if bucket_bytes is None:
        bucket_bytes = int(DEFAULT_BUCKET_MB * _MB)
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = tuple(_path_str(p) for p, _ in flat)
    if len(set(paths)) != len(paths):  # pragma: no cover - pytrees keys are
        raise ValueError("duplicate leaf paths in gradient tree")  # unique
    shapes = tuple(tuple(leaf.shape) for _, leaf in flat)
    dtypes = tuple(jnp.dtype(leaf.dtype) for _, leaf in flat)
    order = sorted(range(len(flat)), key=lambda i: paths[i])

    buckets: list[tuple[int, ...]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in order:
        nbytes = _numel(shapes[i]) * dtypes[i].itemsize
        if cur and (bucket_bytes <= 0 or cur_bytes + nbytes > bucket_bytes):
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
    return BucketPlan(treedef=treedef, paths=paths, shapes=shapes,
                      dtypes=dtypes, buckets=tuple(buckets),
                      bucket_bytes=int(bucket_bytes))


def _leaf_sizes(plan: BucketPlan, members: Sequence[int]) -> list[int]:
    return [_numel(plan.shapes[i]) for i in members]


def _reduce_flat(vec, axis_names: AxisNames, algorithm: str, axis_size: int):
    """One fused collective over a flat payload vector (shard-local view).

    ``psum``: a single all-reduce. ``ring``: reduce-scatter + all-gather —
    the two-phase form whose per-chip traffic is the 2(n-1)/n optimum on a
    ring; the payload is padded to a multiple of the axis size so every
    chip owns an equal scatter chunk.
    """
    if algorithm == "psum" or axis_size <= 1:
        return jax.lax.psum(vec, axis_names)
    if algorithm != "ring":
        raise ValueError(f"unknown all-reduce algorithm {algorithm!r}; "
                         f"expected 'psum' or 'ring'")
    pad = (-vec.size) % axis_size
    if pad:
        vec = jnp.pad(vec, (0, pad))
    chunk = jax.lax.psum_scatter(vec, axis_names, scatter_dimension=0,
                                 tiled=True)
    full = jax.lax.all_gather(chunk, axis_names, tiled=True)
    return full[:full.size - pad] if pad else full


def all_reduce(tree, axis_names: AxisNames, *, axis_size: int,
               bucket_bytes: Optional[int] = None,
               payload_dtype=None, algorithm: str = "psum",
               plan: Optional[BucketPlan] = None):
    """Cross-shard SUM of every leaf of ``tree`` (call inside shard_map).

    One collective per fusion bucket instead of one per leaf. Each bucket
    concatenates its leaves' raveled values — cast to ``payload_dtype``
    when set (bf16 compression) — reduces once, then splits/reshapes/casts
    back to each leaf's own dtype. Leaves keep their exact per-element
    reduction semantics: bucketing changes how many collectives are
    launched, never which values are summed together.

    ``bucket_bytes=0`` (or a plan built that way) reduces per leaf — the
    unfused reference path for A/B measurement.
    """
    if plan is None:
        plan = plan_buckets(tree, bucket_bytes)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if len(leaves) != plan.num_leaves:
        raise ValueError(
            f"plan was built for {plan.num_leaves} leaves, tree has "
            f"{len(leaves)}")
    out: list[Any] = [None] * len(leaves)
    tele = telemetry.get()
    for b, members in enumerate(plan.buckets):
        sizes = _leaf_sizes(plan, members)
        # named_scope labels this bucket's collective in device profiles
        # (jax.profiler / XLA HLO names); the telemetry span runs at TRACE
        # time (once per compile, cat="trace") and carries the bucket's
        # shape metadata into the Chrome trace alongside the runtime
        # phases. Runtime per-bucket device timing lives in the profiler
        # trace — a host-side span cannot see inside one XLA program.
        scope = f"allreduce/bucket{b:02d}"
        with tele.span(f"collective:{scope}", cat="trace",
                       leaves=len(members), elems=sum(sizes)), \
                jax.named_scope(scope):
            if len(members) == 1 and payload_dtype is None:
                # Single-leaf bucket with no dtype policy: skip the
                # ravel/concat round-trip entirely.
                i = members[0]
                out[i] = _reduce_flat(leaves[i].ravel(), axis_names,
                                      algorithm,
                                      axis_size).reshape(plan.shapes[i])
                continue
            # Concatenation needs one dtype; with no explicit payload
            # policy, promote to the bucket's widest member so mixed-dtype
            # buckets never silently downcast a leaf's payload.
            common = (jnp.dtype(payload_dtype) if payload_dtype is not None
                      else jnp.result_type(
                          *(plan.dtypes[i] for i in members)))
            parts = [leaves[i].ravel().astype(common) for i in members]
            buf = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            red = _reduce_flat(buf, axis_names, algorithm, axis_size)
            offset = 0
            for i, n in zip(members, sizes):
                piece = jax.lax.dynamic_slice_in_dim(red, offset, n, 0)
                out[i] = piece.reshape(plan.shapes[i]).astype(plan.dtypes[i])
                offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def all_reduce_gradients(grads, axis_names: AxisNames, *, axis_size: int,
                         options=None):
    """The train-step entry point: SUM ``grads`` across ``axis_names`` per
    the run's :class:`~distributeddeeplearning_tpu.config.AllReduceConfig`
    (``options``; None = defaults). The caller divides by ``axis_size`` to
    turn the Horovod-style ring sum into the gradient average."""
    bucket_mb = getattr(options, "bucket_mb", DEFAULT_BUCKET_MB)
    dtype_name = getattr(options, "dtype", "float32") or "float32"
    algorithm = getattr(options, "algorithm", "psum") or "psum"
    payload = None
    if dtype_name not in ("float32", "bfloat16"):
        raise ValueError(
            f"allreduce dtype {dtype_name!r} not supported; use 'float32' "
            f"(reduce in the gradients' own dtype) or 'bfloat16' "
            f"(compressed payload, fp32 master restored after the reduce)")
    if dtype_name == "bfloat16":
        payload = jnp.bfloat16
    return all_reduce(grads, axis_names, axis_size=axis_size,
                      bucket_bytes=int(float(bucket_mb) * _MB),
                      payload_dtype=payload, algorithm=algorithm)
