"""Utilities: structured metric logging, timing, host helpers."""

from distributeddeeplearning_tpu.utils.logging import MetricLogger  # noqa: F401
