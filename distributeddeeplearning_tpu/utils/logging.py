"""Structured per-step metrics: JSONL to stdout/file, process-0 only.

Replaces the reference's free-form stdout prints (SURVEY.md §5.5); the
benchmark harness parses the same records, so training and benchmarking share
one observability path.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, IO, Optional

import jax


def is_chief() -> bool:
    return jax.process_index() == 0


class MetricLogger:
    """Rank-0 JSONL metric writer with wall-clock throughput accounting.

    ``tensorboard_dir`` additionally mirrors every scalar into TF summaries
    (the observability surface SURVEY.md §5.5 calls for); events are written
    by tf's C++ writer thread, so the hot loop only pays a scalar enqueue.
    """

    def __init__(self, stream: Optional[IO[str]] = None,
                 file_path: Optional[str] = None, enabled: Optional[bool] = None,
                 tensorboard_dir: Optional[str] = None):
        self.stream = stream or sys.stdout
        self.file = open(file_path, "a") if file_path else None
        self.enabled = is_chief() if enabled is None else enabled
        self._tb = None
        if tensorboard_dir and self.enabled:
            import tensorflow as tf

            tf.config.set_visible_devices([], "GPU")
            self._tb = tf.summary.create_file_writer(tensorboard_dir)
        self._last_time: Optional[float] = None
        self._last_step: Optional[int] = None

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def reset_throughput(self) -> None:
        """Invalidate the step-time baseline. Called when wall time between
        two log calls stops meaning training time — a restart-resumed run
        reusing this logger would otherwise fold restore/compile downtime
        into its first throughput sample."""
        self._last_time = None
        self._last_step = None

    def log(self, step: int, metrics: dict[str, Any], *,
            examples_per_step: Optional[int] = None, **extra: Any) -> dict:
        now = time.perf_counter()
        if self._last_step is not None and step < self._last_step:
            # Non-monotonic step (restart resumed from an earlier
            # checkpoint): the elapsed time since the pre-restart log is
            # not step time — drop the baseline instead of emitting a
            # garbage sample at the next log.
            self.reset_throughput()
        record: dict[str, Any] = {"step": int(step)}
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "__float__") else v
        if (examples_per_step and self._last_time is not None
                and step > self._last_step):
            dt = (now - self._last_time) / (step - self._last_step)
            record["step_time_s"] = round(dt, 6)
            record["examples_per_sec"] = round(examples_per_step / dt, 2)
        record.update(extra)
        self._last_time = now
        self._last_step = step
        if self.enabled:
            line = json.dumps(record)
            print(line, file=self.stream, flush=True)
            if self.file:
                self.file.write(line + "\n")
                self.file.flush()
            if self._tb is not None:
                import tensorflow as tf

                with self._tb.as_default():
                    for k, v in record.items():
                        if k != "step" and isinstance(v, (int, float)):
                            tf.summary.scalar(k, v, step=int(step))
        return record

    def close(self) -> None:
        """Release the JSONL file and TB writer; idempotent, and each
        handle is dropped before closing so a failed close cannot leave a
        half-closed logger that double-closes later."""
        f, self.file = self.file, None
        if f is not None:
            f.close()
        tb, self._tb = self._tb, None
        if tb is not None:
            tb.close()
