"""Structured per-step metrics: JSONL to stdout/file, process-0 only.

Replaces the reference's free-form stdout prints (SURVEY.md §5.5); the
benchmark harness parses the same records, so training and benchmarking share
one observability path.
"""

from __future__ import annotations

import json
import sys
from typing import Any, IO, Optional

import jax

from distributeddeeplearning_tpu.observability import telemetry


def is_chief() -> bool:
    return jax.process_index() == 0


class MetricLogger:
    """Rank-0 JSONL metric writer with wall-clock throughput accounting.

    ``tensorboard_dir`` additionally mirrors every scalar into TF summaries
    (the observability surface SURVEY.md §5.5 calls for); events are written
    by tf's C++ writer thread, so the hot loop only pays a scalar enqueue.
    """

    def __init__(self, stream: Optional[IO[str]] = None,
                 file_path: Optional[str] = None, enabled: Optional[bool] = None,
                 tensorboard_dir: Optional[str] = None):
        self.stream = stream or sys.stdout
        self.file = open(file_path, "a") if file_path else None
        self.enabled = is_chief() if enabled is None else enabled
        self._tb = None
        if tensorboard_dir and self.enabled:
            import tensorflow as tf

            tf.config.set_visible_devices([], "GPU")
            self._tb = tf.summary.create_file_writer(tensorboard_dir)
        self._last_time: Optional[float] = None
        self._last_step: Optional[int] = None
        self._flops_per_example: Optional[float] = None
        self._peak_flops: Optional[float] = None

    def set_roofline(self, flops_per_example: Optional[float],
                     peak_flops: Optional[float] = None) -> None:
        """Roofline denominators for throughput records: analytic train
        FLOPs per example (models/flops.py) and the job's TOTAL peak
        (per-chip spec peak x device count). Once set, every record with
        ``examples_per_sec`` also carries ``tflops_per_sec`` and — when the
        peak is known — ``pct_of_peak``, the comparability axis bench
        records and run summaries report (docs/perf_measurement.md)."""
        self._flops_per_example = flops_per_example
        self._peak_flops = peak_flops

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def reset_throughput(self) -> None:
        """Invalidate the step-time baseline. Called when wall time between
        two log calls stops meaning training time — a restart-resumed run
        reusing this logger would otherwise fold restore/compile downtime
        into its first throughput sample."""
        self._last_time = None
        self._last_step = None

    def log(self, step: int, metrics: dict[str, Any], *,
            examples_per_step: Optional[int] = None,
            now_s: Optional[float] = None, **extra: Any) -> dict:
        # One clock for every log-cadence consumer: ``telemetry.now_s``
        # (the straggler monitor and the trace spans read it too). The
        # caller passes the reading it already took for straggler skew
        # math via ``now_s`` so both surfaces see the SAME timestamp —
        # the logger used to read time.perf_counter() here, a second
        # clock that could disagree with the telemetry one by the cost
        # of the straggler allgather.
        now = telemetry.now_s() if now_s is None else float(now_s)
        if self._last_step is not None and step < self._last_step:
            # Non-monotonic step (restart resumed from an earlier
            # checkpoint): the elapsed time since the pre-restart log is
            # not step time — drop the baseline instead of emitting a
            # garbage sample at the next log.
            self.reset_throughput()
        record: dict[str, Any] = {"step": int(step)}
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "__float__") else v
        if (examples_per_step and self._last_time is not None
                and step > self._last_step):
            dt = (now - self._last_time) / (step - self._last_step)
            record["step_time_s"] = round(dt, 6)
            rate = examples_per_step / dt
            record["examples_per_sec"] = round(rate, 2)
            if self._flops_per_example:
                record["tflops_per_sec"] = round(
                    rate * self._flops_per_example / 1e12, 2)
                if self._peak_flops:
                    record["pct_of_peak"] = round(
                        100.0 * rate * self._flops_per_example
                        / self._peak_flops, 1)
        record.update(extra)
        self._last_time = now
        self._last_step = step
        # Single emit path: mirror the numeric fields into the active
        # telemetry registry as gauges so the trace and the JSONL stream
        # can never disagree about what a log step reported.
        tele = telemetry.get()
        if tele.enabled:
            for k, v in record.items():
                if k != "step" and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    tele.gauge(k, v, step=int(step))
        if self.enabled:
            line = json.dumps(record)
            print(line, file=self.stream, flush=True)
            if self.file:
                self.file.write(line + "\n")
                self.file.flush()
            if self._tb is not None:
                import tensorflow as tf

                with self._tb.as_default():
                    for k, v in record.items():
                        if k != "step" and isinstance(v, (int, float)):
                            tf.summary.scalar(k, v, step=int(step))
        return record

    def close(self) -> None:
        """Release the JSONL file and TB writer; idempotent, and each
        handle is dropped before closing so a failed close cannot leave a
        half-closed logger that double-closes later."""
        f, self.file = self.file, None
        if f is not None:
            f.close()
        tb, self._tb = self._tb, None
        if tb is not None:
            tb.close()
