"""HuggingFace checkpoint → framework params (BERT / GPT-2 / Llama).

The mapping that tests/test_hf_parity.py proves logit-exact, packaged for
reuse: `tools/import_hf.py` turns a local HF checkpoint directory into an
orbax checkpoint that `train.py --eval-only`, `generate.py`, and resumed
training all consume. Functions take a ``{name: numpy array}`` state dict
(use :func:`state_dict_to_numpy` on a torch state_dict), so this module
never imports torch/transformers itself.

Weight-layout conventions handled here:
- torch ``nn.Linear`` stores (out, in) → transpose to our (in, out) kernels;
- GPT-2's Conv1D already stores (in, out) → no transpose, and its fused
  c_attn splits into query/key/value thirds;
- Llama per-projection weights transpose; GQA K/V keep their narrower
  (kv_heads·head_dim) width;
- BERT's tied MLM decoder reuses word_embeddings, so only the transform,
  LayerNorm, and output bias are mapped for the head.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Mapping


def state_dict_to_numpy(sd: Mapping[str, Any]) -> dict:
    """torch state_dict → plain numpy dict (the input everything here takes)."""
    return {k: v.detach().cpu().numpy() for k, v in sd.items()}


def _dense_t(sd, prefix):
    """torch nn.Linear (out,in) → flax {'kernel': (in,out), 'bias'}."""
    out = {"kernel": sd[prefix + ".weight"].T}
    if prefix + ".bias" in sd:
        out["bias"] = sd[prefix + ".bias"]
    return out


def _ln(sd, prefix):
    return {"scale": sd[prefix + ".weight"], "bias": sd[prefix + ".bias"]}


def llama_params_from_hf(sd: Mapping[str, Any], num_layers: int) -> dict:
    """transformers.LlamaForCausalLM state dict → models/llama.py params."""

    def layer(i):
        p = f"model.layers.{i}."
        return {
            "attention_norm": {"scale": sd[p + "input_layernorm.weight"]},
            "mlp_norm": {"scale": sd[p + "post_attention_layernorm.weight"]},
            "attention": {
                "q_proj": {"kernel": sd[p + "self_attn.q_proj.weight"].T},
                "k_proj": {"kernel": sd[p + "self_attn.k_proj.weight"].T},
                "v_proj": {"kernel": sd[p + "self_attn.v_proj.weight"].T},
                "o_proj": {"kernel": sd[p + "self_attn.o_proj.weight"].T},
            },
            "gate_proj": {"kernel": sd[p + "mlp.gate_proj.weight"].T},
            "up_proj": {"kernel": sd[p + "mlp.up_proj.weight"].T},
            "down_proj": {"kernel": sd[p + "mlp.down_proj.weight"].T},
        }

    params = {
        "embed_tokens": sd["model.embed_tokens.weight"],
        "final_norm": {"scale": sd["model.norm.weight"]},
        **{f"layer{i}": layer(i) for i in range(num_layers)},
    }
    # tie_word_embeddings models (TinyLlama-1.1B chat variants, etc.) have
    # no separate lm_head tensor; ours always materializes the head kernel.
    head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
    params["lm_head"] = {"kernel": head.T}
    return params


def gpt2_params_from_hf(sd: Mapping[str, Any], num_layers: int) -> dict:
    """transformers.GPT2LMHeadModel state dict → models/gpt.py params.

    HF GPT-2 uses Conv1D ((in, out) weights — NOT transposed)."""

    def layer(i):
        p = f"transformer.h.{i}."
        qkv_w = sd[p + "attn.c_attn.weight"]
        qkv_b = sd[p + "attn.c_attn.bias"]
        h = qkv_w.shape[0]
        return {
            "ln1": _ln(sd, p + "ln_1"),
            "ln2": _ln(sd, p + "ln_2"),
            "attention": {
                "query": {"kernel": qkv_w[:, :h], "bias": qkv_b[:h]},
                "key": {"kernel": qkv_w[:, h:2 * h],
                        "bias": qkv_b[h:2 * h]},
                "value": {"kernel": qkv_w[:, 2 * h:], "bias": qkv_b[2 * h:]},
                "output": {"kernel": sd[p + "attn.c_proj.weight"],
                           "bias": sd[p + "attn.c_proj.bias"]},
            },
            "mlp_in": {"kernel": sd[p + "mlp.c_fc.weight"],
                       "bias": sd[p + "mlp.c_fc.bias"]},
            "mlp_out": {"kernel": sd[p + "mlp.c_proj.weight"],
                        "bias": sd[p + "mlp.c_proj.bias"]},
        }

    return {
        "wte": sd["transformer.wte.weight"],
        "wpe": sd["transformer.wpe.weight"],
        "ln_f": _ln(sd, "transformer.ln_f"),
        **{f"layer{i}": layer(i) for i in range(num_layers)},
    }


def bert_params_from_hf(sd: Mapping[str, Any], num_layers: int) -> dict:
    """transformers.BertForMaskedLM state dict → models/bert.py params."""

    def layer(i):
        p = f"bert.encoder.layer.{i}."
        return {
            "attention": {
                "query": _dense_t(sd, p + "attention.self.query"),
                "key": _dense_t(sd, p + "attention.self.key"),
                "value": _dense_t(sd, p + "attention.self.value"),
                "output": _dense_t(sd, p + "attention.output.dense"),
            },
            "attention_ln": _ln(sd, p + "attention.output.LayerNorm"),
            "intermediate": _dense_t(sd, p + "intermediate.dense"),
            "mlp_output": _dense_t(sd, p + "output.dense"),
            "mlp_ln": _ln(sd, p + "output.LayerNorm"),
        }

    return {
        "word_embeddings": sd["bert.embeddings.word_embeddings.weight"],
        "position_embeddings": sd[
            "bert.embeddings.position_embeddings.weight"],
        "type_embeddings": sd["bert.embeddings.token_type_embeddings.weight"],
        "embeddings_ln": _ln(sd, "bert.embeddings.LayerNorm"),
        "mlm_transform": _dense_t(sd, "cls.predictions.transform.dense"),
        "mlm_ln": _ln(sd, "cls.predictions.transform.LayerNorm"),
        "mlm_bias": sd["cls.predictions.bias"],
        **{f"layer{i}": layer(i) for i in range(num_layers)},
    }


def _flat(params: Mapping[str, Any]) -> dict:
    from flax.traverse_util import flatten_dict

    return flatten_dict(dict(params), sep="/")


def llama_params_to_hf(params: Mapping[str, Any], num_layers: int) -> dict:
    """models/llama.py params → transformers.LlamaForCausalLM state dict
    (numpy values; the exact inverse of :func:`llama_params_from_hf`)."""
    f = _flat(params)
    sd = {
        "model.embed_tokens.weight": f["embed_tokens"],
        "model.norm.weight": f["final_norm/scale"],
        "lm_head.weight": f["lm_head/kernel"].T,
    }
    for i in range(num_layers):
        p, q = f"model.layers.{i}.", f"layer{i}/"
        sd[p + "input_layernorm.weight"] = f[q + "attention_norm/scale"]
        sd[p + "post_attention_layernorm.weight"] = f[q + "mlp_norm/scale"]
        for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[p + f"self_attn.{name}.weight"] = (
                f[q + f"attention/{name}/kernel"].T)
        for name in ("gate_proj", "up_proj", "down_proj"):
            sd[p + f"mlp.{name}.weight"] = f[q + f"{name}/kernel"].T
    return sd


def gpt2_params_to_hf(params: Mapping[str, Any], num_layers: int) -> dict:
    """models/gpt.py params → transformers.GPT2LMHeadModel state dict
    (Conv1D layout: no transposes; qkv re-fused)."""
    import numpy as np

    f = _flat(params)
    sd = {
        "transformer.wte.weight": f["wte"],
        "transformer.wpe.weight": f["wpe"],
        "transformer.ln_f.weight": f["ln_f/scale"],
        "transformer.ln_f.bias": f["ln_f/bias"],
        "lm_head.weight": f["wte"],  # tied head
    }
    for i in range(num_layers):
        p, q = f"transformer.h.{i}.", f"layer{i}/"
        for ln, ours in (("ln_1", "ln1"), ("ln_2", "ln2")):
            sd[p + ln + ".weight"] = f[q + ours + "/scale"]
            sd[p + ln + ".bias"] = f[q + ours + "/bias"]
        sd[p + "attn.c_attn.weight"] = np.concatenate(
            [f[q + "attention/query/kernel"], f[q + "attention/key/kernel"],
             f[q + "attention/value/kernel"]], axis=1)
        sd[p + "attn.c_attn.bias"] = np.concatenate(
            [f[q + "attention/query/bias"], f[q + "attention/key/bias"],
             f[q + "attention/value/bias"]])
        sd[p + "attn.c_proj.weight"] = f[q + "attention/output/kernel"]
        sd[p + "attn.c_proj.bias"] = f[q + "attention/output/bias"]
        sd[p + "mlp.c_fc.weight"] = f[q + "mlp_in/kernel"]
        sd[p + "mlp.c_fc.bias"] = f[q + "mlp_in/bias"]
        sd[p + "mlp.c_proj.weight"] = f[q + "mlp_out/kernel"]
        sd[p + "mlp.c_proj.bias"] = f[q + "mlp_out/bias"]
    return sd


def bert_params_to_hf(params: Mapping[str, Any], num_layers: int) -> dict:
    """models/bert.py params → transformers.BertForMaskedLM state dict."""
    f = _flat(params)
    sd = {
        "bert.embeddings.word_embeddings.weight": f["word_embeddings"],
        "bert.embeddings.position_embeddings.weight":
            f["position_embeddings"],
        "bert.embeddings.token_type_embeddings.weight": f["type_embeddings"],
        "bert.embeddings.LayerNorm.weight": f["embeddings_ln/scale"],
        "bert.embeddings.LayerNorm.bias": f["embeddings_ln/bias"],
        "cls.predictions.transform.dense.weight": f["mlm_transform/kernel"].T,
        "cls.predictions.transform.dense.bias": f["mlm_transform/bias"],
        "cls.predictions.transform.LayerNorm.weight": f["mlm_ln/scale"],
        "cls.predictions.transform.LayerNorm.bias": f["mlm_ln/bias"],
        "cls.predictions.bias": f["mlm_bias"],
        # Tied decoder: transformers materializes these on load, but the
        # saved form carries them for strict-load compatibility.
        "cls.predictions.decoder.weight": f["word_embeddings"],
        "cls.predictions.decoder.bias": f["mlm_bias"],
    }
    for i in range(num_layers):
        p, q = f"bert.encoder.layer.{i}.", f"layer{i}/"
        for hf_name, ours in (
                ("attention.self.query", "attention/query"),
                ("attention.self.key", "attention/key"),
                ("attention.self.value", "attention/value"),
                ("attention.output.dense", "attention/output"),
                ("intermediate.dense", "intermediate"),
                ("output.dense", "mlp_output")):
            sd[p + hf_name + ".weight"] = f[q + ours + "/kernel"].T
            sd[p + hf_name + ".bias"] = f[q + ours + "/bias"]
        for hf_name, ours in (("attention.output.LayerNorm", "attention_ln"),
                              ("output.LayerNorm", "mlp_ln")):
            sd[p + hf_name + ".weight"] = f[q + ours + "/scale"]
            sd[p + hf_name + ".bias"] = f[q + ours + "/bias"]
    return sd


EXPORTERS: dict[str, Callable] = {
    "llama": llama_params_to_hf,
    "gpt2": gpt2_params_to_hf,
    "bert": bert_params_to_hf,
}


# model_type (HF config.json) → (converter, num_layers config key)
CONVERTERS: dict[str, tuple[Callable, str]] = {
    "llama": (llama_params_from_hf, "num_hidden_layers"),
    "gpt2": (gpt2_params_from_hf, "n_layer"),
    "bert": (bert_params_from_hf, "num_hidden_layers"),
}

# Tensors a checkpoint may carry that the mapping legitimately does not
# consume: tied-weight duplicates (same storage as the mapped tensor) and
# non-parameter buffers (causal-mask and position-id caches).
_IGNORABLE = re.compile(
    r"(^|\.)(lm_head\.weight"               # tied head duplicate
    r"|cls\.predictions\.decoder\.(weight|bias)"  # BERT tied decoder
    r"|.*attn\.(masked_)?bias"              # GPT-2 causal-mask buffers
    r"|.*\.position_ids"                    # legacy BERT buffer
    r"|.*rotary_emb\.inv_freq)$")           # legacy Llama RoPE buffer


class _TrackedDict(dict):
    """Records key reads so :func:`convert_checked` can detect weights the
    mapping silently dropped (e.g. bias tensors from attention_bias=True
    checkpoints our architectures don't have)."""

    def __init__(self, data):
        super().__init__(data)
        self.accessed: set = set()

    def __getitem__(self, k):
        self.accessed.add(k)
        return super().__getitem__(k)

    def get(self, k, default=None):
        self.accessed.add(k)
        return super().get(k, default)


def convert_checked(family: str, sd: Mapping[str, Any],
                    num_layers: int) -> dict:
    """Run the family converter and FAIL LOUDLY on unconsumed weights —
    a silently dropped tensor means the imported model computes something
    different from the source checkpoint."""
    convert, _ = CONVERTERS[family]
    tracked = _TrackedDict(sd)
    params = convert(tracked, num_layers)
    leftover = {k for k in tracked if k not in tracked.accessed
                and not _IGNORABLE.search(k)}
    if leftover:
        raise ValueError(
            f"{family} checkpoint has {len(leftover)} tensor(s) the "
            f"architecture mapping does not consume (the import would "
            f"silently change the model): {sorted(leftover)[:8]}")
    return params
